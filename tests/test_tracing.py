"""graftscope: histogram math, tracer mechanics, and the engine contracts.

Three layers under test (docs/serving.md "Observability"):

- :class:`~neuronx_distributed_llama3_2_tpu.serving.Histogram` /
  :class:`~...serving.EngineTracer` unit behavior (no engine, no jax);
- the engine contracts: request_info timing fields survive into terminal
  records, ``snapshot()`` keeps its golden key set, ``prometheus()``
  renders valid exposition, the dashboard renders a snapshot;
- **zero interference**: with ``trace_enabled`` the engine's greedy
  outputs, h2d upload counts, and program registry are identical to the
  untraced engine across {sync,async} x {gather,kernel}, the steady-state
  step stays fully resident, and a 200+-step mixed soak (chunked prefill
  + speculation + async + injected faults) exports a valid Chrome trace
  carrying per-request spans, ProgramRecord-tagged dispatch slices, and
  fault/degradation instants.
"""

import dataclasses
import importlib.util
import json
import math
import os

import jax
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.inference import (
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.analysis.graftcheck import audit_programs
from neuronx_distributed_llama3_2_tpu.serving import (
    EngineTracer,
    FaultInjector,
    FaultPlan,
    Histogram,
    PagedConfig,
    PagedServingEngine,
    audit_engine,
    program_label,
)

from tests.test_paged_serving import _dense_outputs, _prompts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TINY = LLAMA_CONFIGS["tiny"]
TINY_KERNEL = dataclasses.replace(TINY, use_paged_kernel=True)


@pytest.fixture(scope="module")
def params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


def _paged(params, gen, paged_cfg, model_cfg=TINY, injector=None):
    eng = InferenceEngine(
        model_cfg, params, max_batch=4, max_seq_len=64, buckets=[8, 16, 32]
    )
    return PagedServingEngine(eng, gen, paged_cfg, injector=injector)


# ---------------------------------------------------------------------------
# Histogram
# ---------------------------------------------------------------------------


def test_histogram_counts_mean_max_and_clamping():
    h = Histogram(1.0, 64.0, 2.0)
    for v in (0.5, 3.0, 10.0, 100.0):  # 100 > hi lands in overflow
        h.observe(v)
    assert h.count == 4
    assert h.max == 100.0
    assert h.mean() == pytest.approx(113.5 / 4)
    h.observe(-5.0)           # negative clamps to 0, still counted
    h.observe(float("nan"))   # NaN clamps to 0, still counted
    assert h.count == 6 and h.max == 100.0


def test_histogram_percentiles_monotonic_and_log_bounded():
    h = Histogram(0.05, 8e5, 2.0)  # the engine's ms bucket spec
    vals = np.random.default_rng(0).lognormal(mean=2.0, sigma=1.0, size=500)
    for v in vals:
        h.observe(float(v))
    p50, p90, p99 = h.percentile(0.5), h.percentile(0.9), h.percentile(0.99)
    assert 0 < p50 <= p90 <= p99 <= h.max
    # estimate and true quantile share a bucket, so the ratio is bounded
    # by the growth factor
    true50 = float(np.percentile(vals, 50))
    assert true50 / 2.0 <= p50 <= true50 * 2.0


def test_histogram_overflow_bucket_reports_max():
    h = Histogram(1.0, 8.0, 2.0)
    for v in (100.0, 200.0, 300.0):
        h.observe(v)
    assert h.percentile(0.5) == 300.0
    assert set(h.snapshot()) == {"count", "mean", "max", "p50", "p90", "p99"}


def test_histogram_prometheus_block():
    h = Histogram(1.0, 8.0, 2.0)  # finite edges 1, 2, 4, 8
    for v in (0.5, 3.0, 100.0):
        h.observe(v)
    lines = h.prometheus_lines("x_ms")
    assert lines[0] == "# TYPE x_ms histogram"
    assert 'x_ms_bucket{le="1"} 1' in lines
    assert 'x_ms_bucket{le="4"} 2' in lines    # cumulative; zero le="2" elided
    assert 'x_ms_bucket{le="+Inf"} 3' in lines
    assert lines[-2] == "x_ms_sum 103.5"
    assert lines[-1] == "x_ms_count 3"


# ---------------------------------------------------------------------------
# EngineTracer
# ---------------------------------------------------------------------------


def test_tracer_disabled_records_nothing():
    tr = EngineTracer(enabled=False)
    tr.begin_step(0)
    with tr.phase("admit"):
        pass
    tr.complete("dispatch", 0.0, 1.0)
    tr.instant("fault")
    tr.request_state(0, "queued")
    tr.end_step()
    assert tr.phase("a") is tr.phase("b")  # shared no-op span, no allocation
    assert all(e["ph"] == "M" for e in tr.chrome_events())  # metadata only


def test_tracer_ring_buffer_bounds_memory():
    tr = EngineTracer(enabled=True, buffer_steps=4)
    for i in range(10):
        tr.begin_step(i)
        tr.complete("dispatch", tr.now())
        tr.end_step(queue=0)
    steps = [e for e in tr.chrome_events() if e.get("cat") == "step"]
    assert [e["args"]["step"] for e in steps] == [6, 7, 8, 9]


def test_tracer_request_spans_and_terminal_retirement():
    tr = EngineTracer(enabled=True)
    for state in ("queued", "prefilling", "active", "finished"):
        tr.request_state(3, state)
    tr.request_state(4, "queued")  # still live
    evs = [e for e in tr.chrome_events() if e.get("tid") == 3 and e["ph"] != "M"]
    assert [e["name"] for e in evs] == ["queued", "prefilling", "active",
                                       "finished"]
    assert [e["ph"] for e in evs] == ["X", "X", "X", "i"]
    # each state slice ends where the next begins (abutting timeline)
    assert evs[0]["ts"] + evs[0]["dur"] == pytest.approx(evs[1]["ts"], abs=0.2)
    assert 3 not in tr._spans and 4 in tr._spans  # terminal span retired


def test_tracer_export_formats(tmp_path):
    tr = EngineTracer(enabled=True)
    tr.begin_step(0)
    tr.instant("fault", kind="device")
    tr.end_step()
    p = tr.export(str(tmp_path / "t.json"))
    with open(p) as f:
        doc = json.load(f)
    assert isinstance(doc["traceEvents"], list)
    assert doc["displayTimeUnit"] == "ms"
    pj = tr.export(str(tmp_path / "t.jsonl"), fmt="jsonl")
    with open(pj) as f:
        lines = [json.loads(ln) for ln in f]
    assert len(lines) == len(doc["traceEvents"])
    with pytest.raises(ValueError, match="unknown trace format"):
        tr.export(str(tmp_path / "t.bin"), fmt="binary")


def test_program_label_renders_kind_and_sorted_meta():
    class R:
        kind = "pdecode"
        meta = {"kv_limit": 8, "gather": False}

    assert program_label(R()) == "pdecode[gather=False,kv_limit=8]"


# ---------------------------------------------------------------------------
# engine contracts: one shared finished engine for the cheap checks
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def done_engine(params):
    gen = GenerationConfig(max_new_tokens=6)
    paged = _paged(
        params, gen,
        PagedConfig(block_size=8, num_blocks=32, trace_enabled=True),
    )
    for p in _prompts(np.random.default_rng(2), (10, 5)):
        paged.submit(p)
    paged.run_to_completion()
    return paged


def test_request_info_timing_survives_into_finished_records(done_engine):
    info = done_engine.request_info(0)
    assert info["status"] == "finished"
    assert info["ttft_ms"] > 0
    assert info["tpot_ms"] > 0          # 6 tokens => 5 inter-token intervals
    assert info["queue_ms"] >= 0
    assert info["prefill_ms"] > 0
    assert info["finished_at"] >= info["first_token_at"] >= info["submitted_at"]


# the stable snapshot schema: dashboards, the metrics_log_every jsonl, and
# the bench records all consume these keys — additions extend this set,
# renames/removals are breaking and must be deliberate
EXPECTED_SNAPSHOT_KEYS = {
    # dataclass counters
    "submitted", "admitted", "admit_blocked", "finished", "truncated",
    "preemptions", "decode_steps", "engine_steps", "compute_dispatches",
    "mixed_dispatches", "prefill_tokens", "prefill_chunks",
    "cached_tokens", "decode_steps_async", "lame_duck_tokens",
    "sync_fallbacks", "lane_syncs", "table_deltas", "h2d_uploads",
    "host_schedule_ms", "device_wait_ms", "tp_size", "kv_dtype",
    "pool_bytes_per_rank", "pool_bytes_total", "draft_tokens",
    "accepted_tokens", "verify_steps", "spec_disabled_lanes",
    # tree speculation (PagedConfig.spec_tree)
    "tree_verify_steps", "tree_draft_tokens", "tree_accept_by_shape",
    "faults_injected", "failed_requests", "lane_quarantines",
    "drafter_faults", "degradation_level", "degradations",
    "audit_violations", "programs_compiled", "prewarm_compiles",
    "steadystate_compiles",
    # tiered KV storage (host-RAM spill tier)
    "blocks_spilled", "blocks_restored", "spill_bytes", "restore_bytes",
    "restore_hits", "restore_fallbacks", "restore_declined",
    "restore_uploads",
    # fused on-device sampling
    "sampled_steps", "host_sample_fallbacks", "rng_reseeds",
    # graftmeter: pad-waste / dispatch-cost counters + cost-ledger gauges
    "decode_pad_tokens", "decode_need_tokens", "prefill_pad_tokens",
    "prefill_need_tokens", "dispatched_flops", "dispatched_bytes",
    "decode_pad_by_rung", "prefill_pad_by_rung", "cost_profiled_programs",
    "hbm_budget_bytes", "hbm_footprint_bytes", "hbm_headroom_bytes",
    "peak_flops_per_chip", "peak_hbm_bw_per_chip", "mfu_by_rung",
    "slo_alerts", "slo_burn_ttft", "slo_burn_tpot",
    # graftserve: front-door gauges + per-class lifecycle/burn tables
    "queued_requests", "active_streams", "cancelled_requests",
    "requests_by_class", "slo_burn_by_class",
    # graftplan: certified policy-table gauges
    "policy_table_id", "policy_table_stale", "policy_simulated_burn",
    # derived
    "prefix_skip_fraction", "accept_rate", "host_schedule_ms_per_step",
    "device_wait_ms_per_step", "dispatches_per_step", "restore_hit_rate",
    # graftmeter derived
    "pad_waste_frac", "decode_pad_frac", "prefill_pad_frac",
    "achieved_flops_per_s", "mfu_est", "bandwidth_util_est",
    # latency histogram summaries
    "ttft_ms", "tpot_ms", "step_latency_ms", "accept_len", "queue_depth",
    # allocator stats (host-tier gauges zero-default when spill is off)
    "num_blocks", "block_size", "active_blocks", "cached_blocks",
    "free_blocks", "block_utilization", "evictions", "cow_copies",
    "host_tier_bytes", "host_tier_budget_bytes", "host_tier_entries",
    "host_tier_evictions",
    # radix index
    "prefix_hit_rate", "radix_nodes", "spilled_nodes",
}


def test_snapshot_golden_keys(done_engine):
    snap = done_engine.metrics.snapshot(
        done_engine.allocator, done_engine.index
    )
    assert set(snap) == EXPECTED_SNAPSHOT_KEYS
    for key in ("ttft_ms", "tpot_ms", "step_latency_ms"):
        assert set(snap[key]) == {"count", "mean", "max", "p50", "p90", "p99"}
        assert snap[key]["count"] > 0
    json.dumps(snap)  # one JSON object, like every other metrics record


def test_prometheus_exposition(done_engine):
    text = done_engine.metrics.prometheus(
        done_engine.allocator, done_engine.index
    )
    assert text.startswith('serving_info{kv_dtype="bf16"} 1\n')
    assert "# TYPE serving_finished counter" in text
    assert "# TYPE serving_degradation_level gauge" in text
    assert "# TYPE serving_block_utilization gauge" in text
    assert "# TYPE serving_ttft_ms histogram" in text
    assert 'serving_ttft_ms_bucket{le="+Inf"} ' in text
    assert "serving_ttft_ms_count " in text
    assert text.endswith("\n")


def test_dashboard_renders_snapshot(done_engine):
    spec = importlib.util.spec_from_file_location(
        "serving_dashboard_mod",
        os.path.join(REPO, "scripts", "serving_dashboard.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    snap = done_engine.metrics.snapshot(
        done_engine.allocator, done_engine.index
    )
    text = mod.render_snapshot(snap)
    assert "ttft" in text and "p50" in text
    assert f"finished {snap['finished']}" in text
    # fused-step panel row: dispatches per engine step + the pmixed count
    assert f"dispatch   {snap['dispatches_per_step']}/step" in text
    assert f"mixed {snap['mixed_dispatches']})" in text


# ---------------------------------------------------------------------------
# zero interference
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def parity(params):
    gen = GenerationConfig(max_new_tokens=6)
    prompts = _prompts(np.random.default_rng(7), (5, 12, 9, 3))
    return gen, prompts, _dense_outputs(params, prompts, gen)


@pytest.mark.parametrize("model_cfg", [TINY, TINY_KERNEL],
                         ids=["gather", "kernel"])
@pytest.mark.parametrize("async_loop", [False, True], ids=["sync", "async"])
def test_tracing_on_parity_matrix(params, parity, model_cfg, async_loop):
    """Tracing enabled must be invisible to the decode math: greedy outputs
    identical to the dense reference, clean invariant audit, and a clean
    graftcheck program audit (GC003: no host transfers in any trace)."""
    gen, prompts, dense = parity
    paged = _paged(
        params, gen,
        PagedConfig(block_size=8, num_blocks=64, async_loop=async_loop,
                    trace_enabled=True, trace_buffer_steps=64),
        model_cfg,
    )
    for p in prompts:
        paged.submit(p)
    assert paged.run_to_completion() == dense
    assert audit_engine(paged) == []
    assert audit_programs(paged) == []
    # the flight recorder actually recorded
    assert any(e["name"] == "dispatch"
               for e in paged.tracer.chrome_events())


def test_tracing_changes_no_uploads_and_no_programs(params, parity):
    """The hard zero-interference counters: identical h2d upload /
    lane-sync / table-delta counts and an identical program-registry key
    set, traced vs untraced (kernel + async, the fullest path)."""
    gen, prompts, dense = parity

    def run(trace):
        paged = _paged(
            params, gen,
            PagedConfig(block_size=8, num_blocks=64, async_loop=True,
                        trace_enabled=trace),
            TINY_KERNEL,
        )
        for p in prompts:
            paged.submit(p)
        out = paged.run_to_completion()
        m = paged.metrics
        return out, (m.h2d_uploads, m.lane_syncs, m.table_deltas), \
            sorted(map(str, paged._programs))

    out_off, counts_off, progs_off = run(False)
    out_on, counts_on, progs_on = run(True)
    assert out_on == out_off == dense
    assert counts_on == counts_off
    assert progs_on == progs_off


@pytest.mark.parametrize("async_loop", [True, False], ids=["async", "sync"])
def test_steady_state_stays_resident_with_tracing_on(params, async_loop):
    """The zero-upload steady state (tests/test_async_serving.py) must hold
    unchanged with the flight recorder running."""
    gen = GenerationConfig(max_new_tokens=24)
    paged = _paged(
        params, gen,
        PagedConfig(block_size=32, num_blocks=8, async_loop=async_loop,
                    trace_enabled=True),
    )
    paged.submit(_prompts(np.random.default_rng(0), (4,))[0])
    paged.step()
    paged.step()
    m = paged.metrics
    for _ in range(12):
        before = (m.h2d_uploads, m.lane_syncs, m.table_deltas)
        assert paged.step()
        assert (m.h2d_uploads, m.lane_syncs, m.table_deltas) == before
    paged.run_to_completion()


def test_tracing_overhead_smoke(params):
    """Host scheduling with tracing on stays within 5% (+0.2 ms absolute
    slack against CPU jitter) of tracing off — min-of-3 per-step host ms
    on warm engines, so compile time never pollutes the comparison."""
    gen = GenerationConfig(max_new_tokens=12)
    prompts = _prompts(np.random.default_rng(4), (6, 9))

    def per_step_ms(trace):
        paged = _paged(
            params, gen,
            PagedConfig(block_size=8, num_blocks=32, trace_enabled=trace),
        )
        best = math.inf
        for _ in range(3):
            h0 = paged.metrics.host_schedule_ms
            s0 = paged.metrics.decode_steps
            for p in prompts:
                paged.submit(p)
            paged.run_to_completion()
            d_host = paged.metrics.host_schedule_ms - h0
            d_steps = paged.metrics.decode_steps - s0
            best = min(best, d_host / max(d_steps, 1))
        return best

    off = per_step_ms(False)
    on = per_step_ms(True)
    assert on <= off * 1.05 + 0.2, (on, off)


# ---------------------------------------------------------------------------
# the acceptance soak: 200+ steps, every serving feature, faults, export
# ---------------------------------------------------------------------------


# tier-1 budget: every contract this acceptance soak spans (trace
# validity, phase coverage, export) has a dedicated in-tier test above;
# the 200-step all-features run rides the slow tier
@pytest.mark.slow
def test_mixed_soak_exports_valid_chrome_trace(params, tmp_path):
    rng = np.random.default_rng(1234)
    gen = GenerationConfig(max_new_tokens=14)
    cfg = PagedConfig(
        block_size=4, num_blocks=24, decode_reserve_blocks=1,
        prefill_chunk_tokens=8, async_loop=True, spec_draft_tokens=4,
        trace_enabled=True, trace_buffer_steps=512,
        degrade_after_faults=2, degrade_window_steps=64,
        degrade_recover_steps=16,
    )
    n_requests = 18
    lengths = rng.integers(3, 32, size=n_requests)
    prompts = []
    for i, n in enumerate(lengths):
        if i % 2 == 0:  # repetitive half so speculation engages
            pat = rng.integers(1, 9, size=3).tolist()
            prompts.append((pat * (int(n) // 3 + 1))[: int(n)])
        else:
            prompts.append(
                rng.integers(0, TINY.vocab_size, size=(int(n),)).tolist()
            )
    arrivals = np.sort(rng.integers(0, 190, size=n_requests)).tolist()
    arrivals[-1] = 205  # pin one straggler so the soak spans 200+ steps
    # scheduled faults inside one degradation window: the second climbs
    # the ladder, so the trace must carry both fault instants and a
    # degradation instant; the device fault yields a failed request
    inj = FaultInjector(FaultPlan(
        seed=7,
        schedule=((5, "device"), (8, "drafter"), (10, "alloc")),
    ))
    paged = _paged(params, gen, cfg, TINY_KERNEL, injector=inj)

    steps, next_req, alive = 0, 0, True
    while alive or next_req < n_requests:
        while next_req < n_requests and arrivals[next_req] <= steps:
            paged.submit(prompts[next_req])
            next_req += 1
        alive = paged.step()
        steps += 1
        assert steps < 3000, "soak did not converge"
    assert steps >= 200
    assert audit_programs(paged) == []  # GC003/GC006 hold under tracing

    # terminal timing: the device-faulted request still reports its span
    infos = [paged.request_info(r) for r in range(n_requests)]
    failed = [i for i in infos if i["status"] == "failed"]
    assert failed and failed[0]["error"]
    assert failed[0]["finished_at"] is not None
    assert failed[0]["submitted_at"] > 0

    # latency distributions populated with the full percentile summary
    snap = paged.metrics.snapshot(paged.allocator, paged.index)
    for key in ("ttft_ms", "tpot_ms", "step_latency_ms", "queue_depth"):
        assert snap[key]["count"] > 0
        assert 0 < snap[key]["p50"] <= snap[key]["p90"] <= snap[key]["p99"]

    # Chrome trace export: valid JSON with per-step phase slices, the
    # ProgramRecord-tagged dispatches, request spans, and the instants
    path = paged.export_trace(str(tmp_path / "soak_trace.json"))
    with open(path) as f:
        evs = json.load(f)["traceEvents"]
    names = {e["name"] for e in evs}
    dispatches = [e for e in evs if e["name"] == "dispatch"]
    assert dispatches and all(e["ph"] == "X" for e in dispatches)
    assert all("dur" in e and "ts" in e for e in dispatches)
    labels = {e["args"]["program"] for e in dispatches}
    assert any("pdecode" in lb for lb in labels), labels
    assert any(e["args"].get("mode") == "verify" for e in dispatches)
    assert "prefill_chunk" in names
    assert any(e["name"] == "fault" and e["ph"] == "i" for e in evs)
    assert any(e["name"] == "degradation" and e["ph"] == "i" for e in evs)
    req_slices = {e["name"] for e in evs
                  if e.get("pid") == 1 and e["ph"] == "X"}
    assert {"queued", "active"} <= req_slices
    assert any(e.get("pid") == 1 and e["ph"] == "i"
               and e["name"] in ("finished", "failed") for e in evs)

    # jsonl export round-trips the same event stream
    jl = paged.export_trace(str(tmp_path / "soak_trace.jsonl"), fmt="jsonl")
    with open(jl) as f:
        assert len([json.loads(ln) for ln in f]) == len(evs)


def test_trace_events_tag_padded_bucket(params):
    """Every dispatch slice names the kv rung it padded into (and the pad
    waste), every prefill slice its token bucket — the flight-recorder
    view of the catalog ladder (docs/serving.md 'Compiled-program
    catalog'), so an out-of-ladder shape is visible in the trace too."""
    gen = GenerationConfig(max_new_tokens=6)
    paged = _paged(
        params, gen,
        PagedConfig(block_size=8, num_blocks=64, trace_enabled=True,
                    trace_buffer_steps=64, prefill_chunk_tokens=6),
        TINY_KERNEL,
    )
    for p in _prompts(np.random.default_rng(5), (4, 9)):
        paged.submit(p)
    paged.run_to_completion()
    evs = paged.tracer.chrome_events()
    dispatches = [e for e in evs if e["name"] == "dispatch"]
    assert dispatches
    for e in dispatches:
        bucket, pad = e["args"]["kv_bucket"], e["args"]["kv_pad"]
        assert bucket in paged._kv_buckets
        assert 0 <= pad < bucket
    prefills = [e for e in evs if e["name"] in ("prefill", "prefill_chunk")]
    assert {e["name"] for e in prefills} == {"prefill", "prefill_chunk"}
    for e in prefills:
        bucket, pad = e["args"]["bucket"], e["args"]["pad"]
        assert bucket in paged._prefill_buckets
        assert 0 <= pad < bucket
