"""graftsched: legality-automaton fixtures, mutation regressions, the
explorer gate in-process, and policy equivalence.

Layered like the other analyzer suites (test_shardlint / test_graftcheck):

- **automaton unit fixtures** — one accepting and one rejecting flat
  trace per edge of :data:`analysis.graftsched.AUTOMATON`, jax-free;
- **trace replay** — ring-buffer seeding, recorded-depth drift
  detection, the GC010 teardown entry point and its suppress switch;
- **seeded mutations** — both historical-bug transforms fire on a
  hand-built trace and raise when the trace has no applicable site;
- **the CI gate in-process** — ``scripts/graftsched_gate.py`` explores
  seeded schedules against a live tiny engine and must exit 0;
- **policy equivalence** — an explicitly constructed FifoPolicy is
  byte-for-byte the engine default (streams, upload counts, compiled
  program set), and ``make_policy`` rejects unknown names.
"""

import importlib.util
import os

import pytest

from neuronx_distributed_llama3_2_tpu.analysis.graftsched import (
    AUTOMATON,
    KNOWN_MUTATIONS,
    ScheduleState,
    advance,
    check_action_trace,
    check_flat,
    check_trace,
    flatten_trace,
    run_seeded_mutations,
)
from neuronx_distributed_llama3_2_tpu.serving.policy import (
    ActionType,
    FifoPolicy,
    StepAction,
    make_policy,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def A(t, mode="", **meta):
    return StepAction(ActionType(t), mode=mode, meta=meta)


# -- automaton unit fixtures: accept + reject per edge ----------------------


def test_sync_step_shape_accepted():
    """The canonical drained FIFO step: readback, admit, prefill, flush,
    dispatch — legal from an in-flight start (async steady state)."""
    assert check_flat([
        A("READBACK", lag=1),
        A("ADMIT", lanes=[0, 1]),
        A("PREFILL_CHUNK", lanes=[0]),
        A("LANE_SET_FLUSH", lanes=[0, 1]),
        A("DECODE_DISPATCH", mode="sync", lanes=[0, 1]),
    ], start_outstanding=1) == []


def test_async_lookahead_depth_one_accepted():
    """Dispatch N+1 before reading N back: transient depth 2 at the
    dispatch is the async pipeline's steady state and must be legal."""
    assert check_flat([
        A("DECODE_DISPATCH", mode="async", lanes=[0]),
        A("READBACK", lag=1),
        A("DECODE_DISPATCH", mode="async", lanes=[0]),
        A("READBACK", lag=1),
    ], start_outstanding=1) == []


def test_admit_and_prefill_require_drained():
    for t in ("ADMIT", "PREFILL_CHUNK"):
        v = check_flat([A(t, lanes=[0])], start_outstanding=1)
        assert len(v) == 1 and "in flight" in v[0].message, t
        assert check_flat([A(t, lanes=[0])]) == []


def test_dispatch_depth_capped_at_one():
    v = check_flat([
        A("DECODE_DISPATCH", lanes=[0]),
        A("DECODE_DISPATCH", lanes=[0]),
        A("DECODE_DISPATCH", lanes=[0]),
    ])
    assert len(v) == 1  # only the third exceeds the depth-1 pipeline
    assert "lookahead depth 2" in v[0].message


def test_dispatch_into_freed_lane_rejected():
    """The host-state race behind GC010's name: FINISH releases lane 1's
    blocks; a dispatch addressing that lane before re-admission races
    host teardown against device KV writes."""
    trace = [
        A("FINISH", lane=1, rid=7),
        A("DECODE_DISPATCH", mode="sync", lanes=[0, 1]),
    ]
    v = check_flat(trace)
    assert len(v) == 1 and "freed lane(s) [1]" in v[0].message
    # re-admission clears the lane: same dispatch becomes legal
    trace.insert(1, A("ADMIT", lanes=[1]))
    assert check_flat(trace) == []


def test_verify_rules():
    assert check_flat([A("VERIFY", lanes=[0])]) == []
    v = check_flat([A("VERIFY", lanes=[0])], start_outstanding=1)
    assert len(v) == 1 and "VERIFY with 1 step(s)" in v[0].message
    v = check_flat([A("FINISH", lane=0, rid=1), A("VERIFY", lanes=[0])])
    assert len(v) == 1 and "freed lane(s) [0]" in v[0].message


def test_mixed_dispatch_rules():
    """The fused mixed-mode edge: same shape as VERIFY (reads back in the
    same step, so no outstanding depth), plus the freed-lane race check
    must cover the packed prefill rows carried in meta, not just the
    decode lanes."""
    assert check_flat([
        A("MIXED_DISPATCH", lanes=[0, 1], prefill_lanes=[2]),
    ]) == []
    v = check_flat(
        [A("MIXED_DISPATCH", lanes=[0])], start_outstanding=1
    )
    assert len(v) == 1 and "MIXED_DISPATCH with 1 step(s)" in v[0].message
    # a freed decode lane is caught ...
    v = check_flat([
        A("FINISH", lane=0, rid=1),
        A("MIXED_DISPATCH", lanes=[0], prefill_lanes=[1]),
    ])
    assert len(v) == 1 and "freed lane(s) [0]" in v[0].message
    # ... and so is a freed lane hiding among the prefill rows
    v = check_flat([
        A("FINISH", lane=2, rid=5),
        A("MIXED_DISPATCH", lanes=[0], prefill_lanes=[2]),
    ])
    assert len(v) == 1 and "freed lane(s) [2]" in v[0].message


def test_readback_rules():
    assert check_flat([A("READBACK", lag=1)], start_outstanding=1) == []
    v = check_flat([A("READBACK")])
    assert len(v) == 1 and "nothing outstanding" in v[0].message
    v = check_flat([A("READBACK", lag=2)], start_outstanding=1)
    assert len(v) == 1 and "lag 2 > 1" in v[0].message


def test_flush_rules():
    """Full-lane syncs donate every resident — drained boundaries only;
    single-entry table deltas are mid-flight-safe by construction."""
    assert check_flat([A("LANE_SET_FLUSH", lanes=[0])]) == []
    v = check_flat([A("LANE_SET_FLUSH", lanes=[0])], start_outstanding=1)
    assert len(v) == 1 and "full-lane sync" in v[0].message
    assert check_flat([A("TABLE_DELTA_FLUSH", lane=0)],
                      start_outstanding=1) == []


def test_release_requires_drained():
    for t in ("FINISH", "PREEMPT"):
        v = check_flat([A(t, lane=0, rid=3)], start_outstanding=1)
        assert len(v) == 1 and "block release" in v[0].message, t
        assert check_flat([A(t, lane=0, rid=3)]) == []


def test_audit_always_legal():
    assert check_flat([A("AUDIT")], start_outstanding=1) == []


def test_advance_does_not_cascade():
    """One bad transition advances the state anyway, so a single missing
    drain yields one finding, not a spurious avalanche downstream."""
    state = ScheduleState(outstanding=1)
    v = advance(state, A("FINISH", lane=0, rid=1), "t")
    assert len(v) == 1 and state.freed == {0}
    assert state.outstanding == 1  # release does not eat the dispatch


# -- engine-format trace replay ---------------------------------------------


def _step(idx, pending, *actions):
    return (idx, pending, list(actions))


def test_check_trace_seeds_from_ring_buffer_head():
    """The ring buffer may have dropped early steps: the first retained
    entry's pending flag seeds the modeled depth, so a trace starting
    mid-pipeline replays clean."""
    assert check_trace([
        _step(40, True, A("READBACK", lag=1),
              A("DECODE_DISPATCH", mode="sync", lanes=[0])),
        _step(41, True, A("READBACK", lag=1)),
    ]) == []


def test_check_trace_flags_recorded_depth_drift():
    """A later entry whose recorded pending flag disagrees with the model
    means an emission site went missing in engine.py — flagged once, then
    resynced so downstream findings stay honest."""
    v = check_trace([
        _step(1, False, A("DECODE_DISPATCH", mode="sync", lanes=[0])),
        _step(2, False, A("ADMIT", lanes=[])),  # model says depth 1
    ])
    assert len(v) == 1
    assert "recorded lookahead depth 0 != modeled 1" in v[0].message


class _FakeEngine:
    def __init__(self, trace, pending):
        self.action_trace = trace
        self._pending = pending


def test_check_action_trace_terminal_depth_and_suppress():
    trace = [_step(1, False, A("DECODE_DISPATCH", mode="sync", lanes=[0]))]
    eng = _FakeEngine(trace, pending=None)  # modeled 1 vs live 0
    v = check_action_trace(eng)
    assert any("live engine depth 0" in f.message for f in v)
    assert check_action_trace(eng, suppress=("GC010",)) == []
    eng = _FakeEngine(trace, pending=("step", [0]))
    assert check_action_trace(eng) == []


def test_findings_carry_rule_and_fingerprint():
    (f,) = check_flat([A("READBACK")])
    assert f.rule == "GC010"
    assert len(f.fingerprint) == 12
    assert "hint:" in f.format()


# -- seeded mutations --------------------------------------------------------


def _legal_trace():
    """Engine-format trace with a finish after a readback and async
    dispatches: sites for both known mutations."""
    return [
        _step(1, False, A("ADMIT", lanes=[0, 1]),
              A("PREFILL_CHUNK", lanes=[0, 1]),
              A("LANE_SET_FLUSH", lanes=[0, 1]),
              A("DECODE_DISPATCH", mode="sync", lanes=[0, 1]),
              A("READBACK", lag=0)),
        _step(2, False, A("DECODE_DISPATCH", mode="async", lanes=[0, 1])),
        _step(3, True, A("READBACK", lag=1),
              A("FINISH", lane=1, rid=1),
              A("DECODE_DISPATCH", mode="sync", lanes=[0])),
    ]


def test_mutations_caught_on_hand_built_trace():
    trace = _legal_trace()
    start, flat = flatten_trace(trace)
    assert start == 0 and check_flat(flat) == []
    results = run_seeded_mutations(trace, seed=0)
    assert set(results) == set(KNOWN_MUTATIONS)
    for name, findings in results.items():
        assert findings, f"mutation {name} not caught"
    caught = {n: {f.message for f in fs} for n, fs in results.items()}
    assert any("block release" in m
               for m in caught["release-before-lame-duck-drain"])
    assert any("full-lane sync" in m
               for m in caught["lane-set-mid-pipeline"])


def test_mutations_raise_on_thin_trace():
    """A workload with no finishes/dispatches certifies nothing; the
    mutation runner refuses rather than vacuously passing."""
    with pytest.raises(ValueError, match="no applicable site"):
        run_seeded_mutations([_step(1, False, A("ADMIT", lanes=[]))])


# -- the CI gate, in-process -------------------------------------------------


def _load_gate():
    spec = importlib.util.spec_from_file_location(
        "graftsched_gate",
        os.path.join(REPO_ROOT, "scripts", "graftsched_gate.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_gate_main_in_process(capsys):
    """The full gate — FIFO baseline + seeded schedules with per-action
    audits, pure trace replay, both mutation regressions — exits 0."""
    gate = _load_gate()
    assert gate.main(["--schedules", "3"]) == 0
    out = capsys.readouterr().out
    assert "graftsched: clean" in out
    assert "2 mutation(s) caught" in out


def test_gate_list_rules(capsys):
    gate = _load_gate()
    assert gate.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "GC010" in out
    for edge in AUTOMATON:
        assert edge["action"] in out


# -- policy equivalence ------------------------------------------------------


def test_make_policy_registry():
    assert type(make_policy("fifo")) is FifoPolicy
    with pytest.raises(ValueError, match="unknown step_policy"):
        make_policy("round-robin")


def test_explicit_fifo_policy_is_engine_default():
    """PagedConfig(step_policy='fifo'), policy=FifoPolicy() and the bare
    default must be indistinguishable: identical streams, upload counts
    and compiled-program sets."""
    gate = _load_gate()
    factory = gate.make_engine_factory()

    def run(policy):
        eng = factory(policy)
        out = eng.run_to_completion()
        assert check_action_trace(eng) == []
        return eng, out

    eng_default, out_default = run(None)
    eng_fifo, out_fifo = run(FifoPolicy())
    assert out_fifo == out_default
    assert (eng_fifo.metrics.h2d_uploads
            == eng_default.metrics.h2d_uploads)
    assert (set(eng_fifo._programs.keys())
            == set(eng_default._programs.keys()))


# -- docs parity -------------------------------------------------------------


def test_docs_list_every_rule():
    """docs/static_analysis.md documents every exported rule id — the
    SL catalogue, the GC catalogue, and every automaton action — so
    ``--list-rules`` and the docs cannot drift apart silently."""
    from neuronx_distributed_llama3_2_tpu.analysis.graftcheck import GC_RULES
    from neuronx_distributed_llama3_2_tpu.analysis.shardlint import RULES

    with open(os.path.join(REPO_ROOT, "docs", "static_analysis.md")) as fh:
        doc = fh.read()
    for rule in list(RULES) + list(GC_RULES):
        assert rule in doc, f"{rule} missing from docs/static_analysis.md"
    for edge in AUTOMATON:
        assert edge["action"] in doc
