"""Mllama generation tests: greedy continuation parity vs HF
MllamaForConditionalGeneration.generate on the tiny config."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from neuronx_distributed_llama3_2_tpu.inference.mllama_decode import MllamaDecoder

import sys
import os

sys.path.insert(0, os.path.dirname(__file__))
from test_mllama import TINY, _hf_tiny, _inputs  # noqa: E402


@pytest.fixture(scope="module")
def setup():
    from neuronx_distributed_llama3_2_tpu.models.mllama import (
        mllama_params_from_hf,
    )

    hf = _hf_tiny()
    params = mllama_params_from_hf(hf.state_dict(), TINY)
    return hf, params


def test_generate_matches_hf_greedy(setup):
    import torch

    hf, params = setup
    pix, ids, ar_ids, ar_mask, xmask = _inputs(b=2, s=12)
    # single-sequence decode: row 0 (attends image 0's first tile from pos 4)
    pix, ids, ar_ids, ar_mask, xmask = (
        pix[:1], ids[:1], ar_ids[:1], ar_mask[:1], xmask[:1]
    )

    with torch.no_grad():
        ref = hf.generate(
            input_ids=torch.tensor(ids),
            pixel_values=torch.tensor(pix),
            aspect_ratio_ids=torch.tensor(ar_ids),
            aspect_ratio_mask=torch.tensor(ar_mask),
            cross_attention_mask=torch.tensor(xmask),
            max_new_tokens=10,
            do_sample=False,
        )[0, ids.shape[1]:].tolist()

    dec = MllamaDecoder(TINY, params, max_seq_len=64)
    out = dec.generate(
        list(ids[0]),
        jnp.asarray(pix), jnp.asarray(ar_ids), jnp.asarray(ar_mask),
        jnp.asarray(xmask), max_new_tokens=10,
    )
    assert out == ref, (out, ref)


def test_prefill_logits_match_full_forward(setup):
    """Decode-path prefill logits == the training model's forward."""
    from neuronx_distributed_llama3_2_tpu.models.mllama import (
        MllamaForConditionalGeneration,
        prepare_cross_attention_mask,
    )

    _, params = setup
    pix, ids, ar_ids, ar_mask, xmask = _inputs(b=2, s=12)
    pix, ids, ar_ids, ar_mask, xmask = (
        pix[:1], ids[:1], ar_ids[:1], ar_mask[:1], xmask[:1]
    )
    model = MllamaForConditionalGeneration(TINY)
    ref = jax.jit(model.__call__)(
        params, jnp.asarray(ids), jnp.asarray(pix), jnp.asarray(ar_ids),
        jnp.asarray(ar_mask), jnp.asarray(xmask),
    )

    dec = MllamaDecoder(TINY, params, max_seq_len=32)
    from neuronx_distributed_llama3_2_tpu.inference.mllama_decode import (
        MllamaCache,
    )

    _, ck, cv = dec.precompute_cross_kv(
        jnp.asarray(pix), jnp.asarray(ar_ids), jnp.asarray(ar_mask)
    )
    t = TINY.text
    cache = MllamaCache(
        k=[jnp.zeros((1, 32, t.num_kv_heads, t.head_dim), t.dtype)
           for _ in dec._self_layers],
        v=[jnp.zeros((1, 32, t.num_kv_heads, t.head_dim), t.dtype)
           for _ in dec._self_layers],
        cross_k=ck, cross_v=cv,
    )
    bias, full = prepare_cross_attention_mask(
        jnp.asarray(xmask), TINY.vision.num_patches
    )
    logits, _ = jax.jit(dec.forward)(
        params, cache, jnp.asarray(ids, jnp.int32),
        jnp.zeros((1,), jnp.int32), bias, full,
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref), atol=2e-4, rtol=1e-3
    )


def test_generate_eos_and_zero_budget(setup):
    _, params = setup
    pix, ids, ar_ids, ar_mask, xmask = _inputs(b=2, s=12)
    pix, ids, ar_ids, ar_mask, xmask = (
        pix[:1], ids[:1], ar_ids[:1], ar_mask[:1], xmask[:1]
    )
    dec = MllamaDecoder(TINY, params, max_seq_len=64)
    args = (list(ids[0]), jnp.asarray(pix), jnp.asarray(ar_ids),
            jnp.asarray(ar_mask), jnp.asarray(xmask))
    assert dec.generate(*args, max_new_tokens=0) == []
    full = dec.generate(*args, max_new_tokens=6)
    # treating the first emitted token as EOS stops after exactly one token
    assert dec.generate(*args, max_new_tokens=6, eos_token_id=full[0]) == full[:1]


def test_quantized_tree_serves_in_jit(setup):
    """int8 trees serve through MllamaDecoder with in-jit dequant (was a
    NotImplementedError refusal): generation equals serving the
    host-dequantized tree — identical computation, exact match."""
    from neuronx_distributed_llama3_2_tpu.quantization import (
        QuantizedTensor,
        dequantize_params,
        quantize_params,
    )

    _, params = setup
    pix, ids, ar_ids, ar_mask, xmask = _inputs(b=2, s=12)
    pix, ids, ar_ids, ar_mask, xmask = (
        pix[:1], ids[:1], ar_ids[:1], ar_mask[:1], xmask[:1]
    )
    args = (
        list(ids[0]), jnp.asarray(pix), jnp.asarray(ar_ids),
        jnp.asarray(ar_mask), jnp.asarray(xmask),
    )

    qparams = quantize_params(params)
    n_q = sum(
        isinstance(l, QuantizedTensor)
        for l in jax.tree.leaves(
            qparams, is_leaf=lambda l: isinstance(l, QuantizedTensor)
        )
    )
    assert n_q > 0, "quantize_params matched no mllama kernels"
    # coverage: text self+cross attention, vision attention/MLP and the
    # projector all quantize (review finding: only o-projections matched
    # before the Mllama patterns were added to DEFAULT_TARGETS)
    from neuronx_distributed_llama3_2_tpu.quantization.quantize import _walk

    q_paths = []
    _walk(qparams, lambda p, l: q_paths.append(p)
          if isinstance(l, QuantizedTensor) else l)
    assert any("cross_attn/q/kernel" in p for p in q_paths), q_paths[:10]
    assert any("vision_model" in p and "self_attn/q/kernel" in p for p in q_paths)
    assert any("mlp/fc1/kernel" in p for p in q_paths)
    assert any("multi_modal_projector" in p for p in q_paths)

    out_q = MllamaDecoder(TINY, qparams, max_seq_len=64).generate(
        *args, max_new_tokens=8
    )
    deq = dequantize_params(qparams, TINY.text.dtype)
    out_ref = MllamaDecoder(TINY, deq, max_seq_len=64).generate(
        *args, max_new_tokens=8
    )
    assert out_q == out_ref, (out_q, out_ref)
