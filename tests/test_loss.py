"""Vocab-parallel CE vs dense CE (reference tolerance pattern
test/integration/parallel_layers test_loss_functions)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_llama3_2_tpu.parallel import loss as L, state as ps
from neuronx_distributed_llama3_2_tpu.utils import compat


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_parallel_xent_matches_dense(smoothing):
    st = ps.initialize_model_parallel(tensor_model_parallel_size=4)
    mesh = st.mesh
    B, S, V = 2, 8, 64
    k = jax.random.PRNGKey(0)
    logits = jax.random.normal(k, (B, S, V)) * 3.0
    labels = jax.random.randint(jax.random.fold_in(k, 1), (B, S), 0, V)

    dense = L.cross_entropy(logits, labels, smoothing)
    logits_s = jax.device_put(logits, NamedSharding(mesh, P(None, None, "tp")))
    with compat.set_mesh(mesh):
        par = jax.jit(lambda lg, lb: L.parallel_cross_entropy(lg, lb, smoothing))(
            logits_s, labels
        )
    np.testing.assert_allclose(np.asarray(par), np.asarray(dense), rtol=1e-5, atol=1e-6)


def test_parallel_xent_grad_matches_dense():
    st = ps.initialize_model_parallel(tensor_model_parallel_size=4)
    mesh = st.mesh
    B, V = 4, 32
    k = jax.random.PRNGKey(2)
    logits = jax.random.normal(k, (B, V))
    labels = jax.random.randint(jax.random.fold_in(k, 3), (B,), 0, V)

    gd = jax.grad(lambda lg: L.cross_entropy(lg, labels).mean())(logits)
    logits_s = jax.device_put(logits, NamedSharding(mesh, P(None, "tp")))
    with compat.set_mesh(mesh):
        gp = jax.jit(
            jax.grad(lambda lg: L.parallel_cross_entropy(lg, labels).mean())
        )(logits_s)
    # softmax - onehot backward (reference loss_functions.py:103)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gd), rtol=1e-5, atol=1e-6)


def test_xent_sanity_perfect_prediction():
    logits = jnp.full((1, 4), -20.0).at[0, 2].set(20.0)
    labels = jnp.array([2])
    assert float(L.cross_entropy(logits, labels)[0]) < 1e-5
