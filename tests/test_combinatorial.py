"""Combinatorial parallelism matrix (reference test strategy, SURVEY §4:
``test_TP8_SP1_SC0_PP4_Zero1Opt1_FP32.txt`` configs driven over a fixed
4-layer llama). The invariant: the first train-step loss and grad norm are
the SAME number no matter how the computation is sharded — TP/SP/PP/ZeRO/
grad-accum/remat only change placement and scheduling, never math."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state
from neuronx_distributed_llama3_2_tpu.pipeline import PipelinedCausalLM
from neuronx_distributed_llama3_2_tpu.trainer import (
    OptimizerConfig,
    TrainingConfig,
    initialize_parallel_model,
    make_train_step,
)

TINY = LLAMA_CONFIGS["tiny"]
GBS, SEQ = 8, 32


def _oracle():
    """Unsharded single-device loss/grad-norm for the fixed batch."""
    parallel_state.destroy_model_parallel()
    cfg = TrainingConfig(
        optimizer=OptimizerConfig(zero_one_enabled=False, warmup_steps=1)
    )
    cfg.initialize(devices=jax.devices()[:1])
    try:
        model = LlamaForCausalLM(TINY)
        state, _ = initialize_parallel_model(model, cfg)
        step = make_train_step(model, cfg)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, TINY.vocab_size, (GBS, SEQ)),
            jnp.int32,
        )
        _, m = step(state, {"input_ids": ids, "labels": ids})
        return float(m["loss"]), float(m["grad_norm"])
    finally:
        parallel_state.destroy_model_parallel()


@pytest.fixture(scope="module")
def oracle():
    return _oracle()


# the reference's var=value combo files, spelled as parametrize ids
# default tier keeps one combo per dimension (tp+sp, grad-accum, pp, 1f1b);
# the full matrix runs in the opt-in slow tier (pytest -m slow) — same
# split the reference makes between per-PR tests and its combinatorial
# integration matrix (SURVEY §4)
_S = pytest.mark.slow
COMBOS = [
    # (tp, sp, pp, zero1, microbatches, remat, schedule)
    ("TP2_SP0_PP1_Z0_MB1", 2, False, 1, False, 1, "none", None),
    pytest.param("TP2_SP1_PP1_Z1_MB1", 2, True, 1, True, 1, "none", None, marks=_S),
    pytest.param("TP4_SP1_PP1_Z1_MB1", 4, True, 1, True, 1, "none", None, marks=_S),
    ("TP1_SP0_PP1_Z1_MB2", 1, False, 1, True, 2, "none", None),
    pytest.param("TP1_SP0_PP1_Z1_MB4", 1, False, 1, True, 4, "none", None, marks=_S),
    pytest.param("TP1_SP0_PP2_Z1_MB1", 1, False, 2, True, 1, "none", "gpipe", marks=_S),
    pytest.param("TP2_SP1_PP2_Z1_MB1", 2, True, 2, True, 1, "none", "gpipe", marks=_S),
    ("TP2_SP1_PP2_Z0_1F1B", 2, True, 2, False, 1, "none", "1f1b"),
    pytest.param("TP2_SP1_PP1_Z1_SC", 2, True, 1, True, 1, "selective", None, marks=_S),
    ("TP2_SP1_PP1_Z1_FULLRM", 2, True, 1, True, 1, "full", None),
]


@pytest.mark.parametrize(
    "name,tp,sp,pp,zero1,mb,remat,schedule",
    COMBOS,
    ids=[
        (c.values[0] if hasattr(c, "values") else c[0]) for c in COMBOS
    ],
)
def test_combo_matches_oracle(oracle, name, tp, sp, pp, zero1, mb, remat, schedule):
    want_loss, want_gn = oracle
    parallel_state.destroy_model_parallel()
    cfg = TrainingConfig(
        tensor_parallel_size=tp,
        pipeline_parallel_size=pp,
        sequence_parallel=sp,
        num_microbatches=mb,
        optimizer=OptimizerConfig(zero_one_enabled=zero1, warmup_steps=1),
    )
    cfg.initialize(devices=jax.devices()[:8])
    try:
        model_cfg = dataclasses.replace(TINY, remat=remat)
        model = LlamaForCausalLM(model_cfg)
        if pp > 1:
            model = PipelinedCausalLM(
                model, num_microbatches=4, schedule=schedule
            )
        state, _ = initialize_parallel_model(model, cfg)
        # identical init across meshes: jit-init is seeded by cfg.seed, and
        # tiny is fp32, so parameters agree bit-for-bit with the oracle run
        step = make_train_step(model, cfg)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, TINY.vocab_size, (GBS, SEQ)),
            jnp.int32,
        )
        _, m = step(state, {"input_ids": ids, "labels": ids})
        np.testing.assert_allclose(float(m["loss"]), want_loss, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(
            float(m["grad_norm"]), want_gn, rtol=5e-4, atol=5e-4
        )
    finally:
        parallel_state.destroy_model_parallel()
