"""Parallel layer numerical-parity tests (pattern of the reference's
test/integration/parallel_layers/test_layers.py:44-82 — parallel vs serial
math, same init, loss/grad error < 1e-3 — but hardware-free on the CPU mesh).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_llama3_2_tpu.parallel import layers, state as ps
from neuronx_distributed_llama3_2_tpu.utils import compat


@pytest.fixture
def tp4():
    st = ps.initialize_model_parallel(tensor_model_parallel_size=4)
    return st


def _shard_params(layer, params, mesh):
    return layers.shard_pytree(params, layer.specs(), mesh)


def test_column_row_mlp_parity(tp4):
    mesh = tp4.mesh
    col = layers.ColumnParallelLinear(16, 64, use_bias=True)
    row = layers.RowParallelLinear(64, 16, use_bias=True)
    k = jax.random.PRNGKey(0)
    pc = col.init(jax.random.fold_in(k, 1))
    pr = row.init(jax.random.fold_in(k, 2))
    x = jax.random.normal(k, (2, 8, 16))

    def loss(pc, pr, x):
        return (row(pr, jax.nn.gelu(col(pc, x))) ** 2).mean()

    dense = loss(pc, pr, x)  # un-meshed path: constraints no-op'd via same fn
    pc_s = _shard_params(col, pc, mesh)
    pr_s = _shard_params(row, pr, mesh)
    with compat.set_mesh(mesh):
        sharded = jax.jit(loss)(pc_s, pr_s, x)
        gs = jax.jit(jax.grad(loss, argnums=(0, 1)))(pc_s, pr_s, x)
    gd = jax.grad(loss, argnums=(0, 1))(pc, pr, x)
    np.testing.assert_allclose(float(sharded), float(dense), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(gs), jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_parallel_embedding_parity(tp4):
    mesh = tp4.mesh
    emb = layers.ParallelEmbedding(128, 32)
    k = jax.random.PRNGKey(0)
    p = emb.init(k)
    ids = jax.random.randint(jax.random.fold_in(k, 1), (2, 8), 0, 128)
    ref = np.asarray(p["embedding"])[np.asarray(ids)]
    p_s = _shard_params(emb, p, mesh)
    with compat.set_mesh(mesh):
        out = jax.jit(lambda p, i: emb(p, i))(p_s, ids)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)


def test_gqa_qkv_sharded_and_replicated_kv(tp4):
    mesh = tp4.mesh
    # num_kv_heads=4 divisible by tp=4 -> sharded; =2 -> replicated
    for kvh, expect_sharded in [(4, True), (2, False)]:
        qkv = layers.GQAQKVColumnParallelLinear(
            hidden_size=32, num_heads=8, num_kv_heads=kvh, head_dim=4
        )
        assert qkv._kv_sharded() == expect_sharded
        k = jax.random.PRNGKey(0)
        p = qkv.init(k)
        assert p["q_kernel"].shape == (32, 32)
        assert p["k_kernel"].shape == (32, kvh * 4)
        x = jax.random.normal(k, (2, 8, 32))
        p_s = _shard_params(qkv, p, mesh)
        with compat.set_mesh(mesh):
            q, kk, v = jax.jit(lambda p, x: qkv(p, x))(p_s, x)
        np.testing.assert_allclose(
            np.asarray(q), np.asarray(x @ p["q_kernel"]), rtol=2e-5, atol=1e-6
        )


def test_divide():
    assert layers.divide(8, 4) == 2
    with pytest.raises(ValueError):
        layers.divide(7, 4)


def test_kv_flat_sharding_when_tp_exceeds_kv_heads():
    """tp=8 > kv_heads=4: K/V kernels shard over the flat output dim (1/tp
    weight per device) instead of silently replicating (VERDICT weak #5; the
    GSPMD form of the reference's kv_size_multiplier, qkv_linear.py:454)."""
    import dataclasses

    from neuronx_distributed_llama3_2_tpu.models.llama import (
        LLAMA_CONFIGS,
        LlamaForCausalLM,
    )

    cfg = dataclasses.replace(
        LLAMA_CONFIGS["tiny"], num_heads=8, num_kv_heads=4, head_dim=16,
        hidden_size=64,
    )
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)), jnp.int32
    )
    ref = jax.jit(model.__call__)(params, ids)

    ps.initialize_model_parallel(tensor_model_parallel_size=8)
    layer = layers.GQAQKVColumnParallelLinear(
        hidden_size=64, num_heads=8, num_kv_heads=4, head_dim=16
    )
    assert not layer._kv_sharded() and layer._kv_flat_sharded()
    specs = layer.specs()
    assert specs["k_kernel"] == P(None, "tp")

    sharded = layers.shard_pytree(params, model.specs())
    # stacked k kernel (L, H, kv*D) genuinely tp-sharded, not replicated
    kk = sharded["layers"]["attn"]["qkv"]["k_kernel"]
    assert kk.sharding.spec[-1] == "tp"
    out = jax.jit(model.__call__)(sharded, ids)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=2e-4, rtol=1e-4,
    )


def test_kv_falls_back_to_replication_when_flat_indivisible():
    """tp=8, kv=3 (kv*D=48 not divisible by 8): stays replicated."""
    layer = layers.GQAQKVColumnParallelLinear(
        hidden_size=64, num_heads=6, num_kv_heads=3, head_dim=16,
        tensor_parallel_size=8,
    )
    assert not layer._kv_sharded() and not layer._kv_flat_sharded()
    assert layer.specs()["k_kernel"] == P(None, None)


def test_kv_flat_sharding_requires_q_divisible():
    """heads=4 < tp=8: flat sharding must NOT engage (repeating kv to 8
    heads with 4 q heads would collapse the GQA group to zero)."""
    layer = layers.GQAQKVColumnParallelLinear(
        hidden_size=64, num_heads=4, num_kv_heads=2, head_dim=16,
        tensor_parallel_size=8,
    )
    assert not layer._kv_flat_sharded()
    assert layer.kv_repeat_factor() == 1
