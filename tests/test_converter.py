"""Checkpoint converter tests (VERDICT missing #7): HF↔native roundtrips and
the CLI entry points (reference scripts/checkpoint_converter.py:238,393)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
    params_from_hf,
    params_to_hf,
)
from neuronx_distributed_llama3_2_tpu.scripts.checkpoint_converter import main as cli

TINY = LLAMA_CONFIGS["tiny"]


def _tiny_params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


def test_hf_roundtrip_exact():
    """params → HF state dict → params is the identity (fp32 tiny)."""
    params = _tiny_params()
    back = params_from_hf(params_to_hf(params, TINY), TINY)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a, np.float32), np.asarray(b, np.float32))


def test_hf_state_dict_names_match_transformers_convention():
    sd = params_to_hf(_tiny_params(), TINY)
    assert "model.embed_tokens.weight" in sd
    assert "model.layers.0.self_attn.q_proj.weight" in sd
    assert "model.layers.0.mlp.gate_proj.weight" in sd
    assert "model.norm.weight" in sd
    # tiny ties embeddings: no lm_head in the exported dict (HF convention)
    assert ("lm_head.weight" in sd) == (not TINY.tie_word_embeddings)
    # torch Linear layout (out, in)
    assert sd["model.layers.0.mlp.gate_proj.weight"].shape == (
        TINY.intermediate_size,
        TINY.hidden_size,
    )


def test_cli_hf_to_native_to_hf(tmp_path):
    from safetensors.numpy import save_file

    params = _tiny_params()
    sd = params_to_hf(params, TINY)
    hf_dir = tmp_path / "hf"
    hf_dir.mkdir()
    save_file({k: np.ascontiguousarray(v) for k, v in sd.items()},
              str(hf_dir / "model.safetensors"))

    ckpt_dir = tmp_path / "native"
    cli([
        "--direction", "hf-to-native", "--model", "tiny",
        "--input", str(hf_dir), "--output", str(ckpt_dir), "--tag", "imported",
    ])
    assert (ckpt_dir / "imported" / "done").exists()

    out_dir = tmp_path / "hf_back"
    cli([
        "--direction", "native-to-hf", "--model", "tiny",
        "--input", str(ckpt_dir), "--output", str(out_dir), "--tag", "imported",
    ])
    from safetensors.numpy import load_file

    back = load_file(str(out_dir / "model.safetensors"))
    assert set(back) == set(sd)
    for k in sd:
        np.testing.assert_allclose(back[k], np.asarray(sd[k], np.float32), atol=1e-6)
    assert (out_dir / "config.json").exists()


def test_cli_strip_optimizer(tmp_path):
    from neuronx_distributed_llama3_2_tpu.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    params = _tiny_params()
    fake_opt = {"m": jax.tree.map(jnp.zeros_like, params)}
    src = tmp_path / "train"
    save_checkpoint(str(src), tag="step_5", model=params, optimizer=fake_opt)
    dst = tmp_path / "export"
    cli([
        "--direction", "strip-optimizer", "--model", "tiny",
        "--input", str(src), "--output", str(dst), "--tag", "step_5",
    ])
    template = jax.eval_shape(LlamaForCausalLM(TINY).init, jax.random.key(0))
    loaded = load_checkpoint(str(dst), tag="step_5", model=template)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded["model"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # optimizer state not carried over
    with pytest.raises(Exception):
        load_checkpoint(str(dst), tag="step_5", optimizer=fake_opt)


def test_cli_copy_tag_with_optimizer(tmp_path):
    """copy-tag: template-free offline move of a full training checkpoint
    (model + optimizer state) to a new root/tag; loads back identically
    (the role of the reference's convert_zero_checkpoints CLI,
    optimizer/convert_zero_checkpoints.py:176 — dp resharding itself
    dissolves into load-time specs)."""
    from neuronx_distributed_llama3_2_tpu.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    model = LlamaForCausalLM(TINY)
    params = model.init(jax.random.key(0))
    fake_opt = {"mu": jax.tree.map(lambda p: p * 0.5, params), "step": jnp.int32(7)}
    src, dst = tmp_path / "src", tmp_path / "dst"
    save_checkpoint(
        str(src), tag="step100", model=params, optimizer=fake_opt,
        scheduler={"lr": 1e-4}, user_content={"note": "x"},
    )

    cli([
        "--direction", "copy-tag", "--input", str(src),
        "--output", str(dst), "--tag", "step100", "--out-tag", "exported",
    ])

    loaded = load_checkpoint(
        str(dst), tag="exported",
        model=jax.eval_shape(lambda: params),
        optimizer=jax.eval_shape(lambda: fake_opt),
    )
    assert loaded["scheduler"] == {"lr": 1e-4}
    assert loaded["user_content"] == {"note": "x"}
    for a, b in zip(jax.tree.leaves(loaded["model"]), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree.leaves(loaded["optimizer"]), jax.tree.leaves(fake_opt)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cli_hf_to_native_all_families(tmp_path):
    """The registry covers every family: import a tiny HF checkpoint of each
    architecture through the CLI."""
    import torch
    from safetensors.numpy import save_file

    from neuronx_distributed_llama3_2_tpu.checkpoint import load_checkpoint
    from neuronx_distributed_llama3_2_tpu.scripts.checkpoint_converter import (
        _resolve_model,
    )

    # build tiny HF models per family (reuse the parity-test constructors)
    from tests.test_dbrx import _hf_tiny_dbrx, _hf_tiny_mixtral
    from tests.test_gptneox import _hf_codegen, _hf_neox
    from tests.test_bert import _hf_bert

    cases = {
        "tiny-moe": _hf_tiny_mixtral(),
        "tiny-dbrx": _hf_tiny_dbrx(),
        "tiny-neox": _hf_neox(),
        "tiny-codegen": _hf_codegen(),
        "tiny-bert": _hf_bert(),
    }
    for name, hf in cases.items():
        hf_dir = tmp_path / f"hf_{name}"
        hf_dir.mkdir()
        sd = {
            k: v.detach().numpy().astype(np.float32)
            for k, v in hf.state_dict().items()
        }
        save_file(sd, str(hf_dir / "model.safetensors"))
        out = tmp_path / f"native_{name}"
        cli([
            "--direction", "hf-to-native", "--model", name,
            "--input", str(hf_dir), "--output", str(out), "--tag", "imported",
        ])
        entry = _resolve_model(name)
        template = jax.eval_shape(
            entry["model_cls"](entry["config"]).init, jax.random.key(0)
        )
        loaded = load_checkpoint(str(out), tag="imported", model=template)
        assert loaded is not None, name


def test_cli_unknown_model_lists_choices():
    with pytest.raises(KeyError, match="tiny-neox"):
        cli([
            "--direction", "hf-to-native", "--model", "nope",
            "--input", "/tmp/x", "--output", "/tmp/y",
        ])


@pytest.mark.slow
def test_generate_cli_arg_validation():
    """examples/generate.py argument paths: unknown model lists choices,
    BERT is refused by the decode dispatcher, missing prompt errors, and
    malformed --prompt-ids fail rather than generate garbage."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "examples", "generate.py")
    env = dict(os.environ)
    # subprocesses must not touch the real-chip backend: force cpu AND strip
    # the axon sitecustomize (its register() call can block on a dead relay
    # before JAX_PLATFORMS is ever consulted)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    ) or os.getcwd()

    def run(*args):
        return subprocess.run(
            [sys.executable, script, *args],
            capture_output=True, text=True, env=env, timeout=240,
        )

    r = run("--model", "nope", "--random-init", "--prompt-ids", "1,2")
    assert r.returncode != 0 and "tiny-neox" in (r.stderr + r.stdout)

    r = run(
        "--model", "tiny-bert", "--random-init", "--prompt-ids", "1,2",
        "--cpu-devices", "2",
    )
    assert r.returncode != 0
    assert "bidirectional" in (r.stderr + r.stdout)

    r = run("--model", "tiny", "--random-init", "--cpu-devices", "2")
    assert r.returncode != 0
    assert "--prompt" in (r.stderr + r.stdout)

    r = run(
        "--model", "tiny", "--random-init", "--prompt-ids", "1,a,2",
        "--cpu-devices", "2",
    )
    assert r.returncode != 0  # malformed ids must not silently generate



def test_to_hf_roundtrip_all_families():
    """Native→HF for every family (VERDICT r2 missing #3): (a) to_hf values
    bit-match the original HF state dict on every exported key; (b)
    from_hf(to_hf(params)) is the identity — no information loss."""
    from neuronx_distributed_llama3_2_tpu.scripts.checkpoint_converter import (
        _resolve_model,
    )
    from tests.test_dbrx import _hf_tiny_dbrx, _hf_tiny_mixtral
    from tests.test_gptneox import _hf_codegen, _hf_neox
    from tests.test_bert import _hf_bert

    cases = {
        "tiny-moe": _hf_tiny_mixtral(),
        "tiny-dbrx": _hf_tiny_dbrx(),
        "tiny-neox": _hf_neox(),
        "tiny-codegen": _hf_codegen(),
        "tiny-bert": _hf_bert(),
    }
    for name, hf in cases.items():
        entry = _resolve_model(name)
        sd = {
            k: v.detach().numpy().astype(np.float32)
            for k, v in hf.state_dict().items()
        }
        params = entry["from_hf"](sd, entry["config"])
        back = entry["to_hf"](params, entry["config"])
        for k, v in back.items():
            assert k in sd, (name, k)
            np.testing.assert_allclose(
                v, sd[k], atol=1e-6, err_msg=f"{name}:{k}"
            )
        again = entry["from_hf"](back, entry["config"])
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(params)[0],
            jax.tree_util.tree_flatten_with_path(again)[0],
        ):
            assert pa == pb
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                err_msg=f"{name}:{pa}",
            )


def test_cli_include_optimizer_export(tmp_path):
    """--include-optimizer: fp32 master + moments exported to
    optimizer/*.safetensors with HF names, elementwise-aligned with the
    weight export (reference optimizer/convert_zero_checkpoints.py:176)."""
    from safetensors.numpy import load_file

    from neuronx_distributed_llama3_2_tpu.checkpoint import save_checkpoint
    from neuronx_distributed_llama3_2_tpu.trainer.optimizer import (
        OptimizerState,
    )

    params = _tiny_params()
    opt = OptimizerState(
        step=jnp.asarray(7, jnp.int32),
        master=jax.tree.map(lambda p: p.astype(jnp.float32), params),
        mu=jax.tree.map(lambda p: jnp.full(p.shape, 0.25, jnp.float32), params),
        nu=jax.tree.map(lambda p: jnp.full(p.shape, 0.5, jnp.float32), params),
    )
    ckpt = tmp_path / "native"
    save_checkpoint(str(ckpt), tag="trained", model=params, optimizer=opt)

    out = tmp_path / "hf"
    cli([
        "--direction", "native-to-hf", "--model", "tiny",
        "--input", str(ckpt), "--output", str(out), "--tag", "trained",
        "--include-optimizer",
    ])
    exported = load_file(str(out / "optimizer" / "optimizer.safetensors"))
    meta = json.loads((out / "optimizer" / "optimizer.json").read_text())
    assert meta["kinds"] == ["master", "mu", "nu"]
    assert meta["step"] == 7
    # moments carry the HF layout transforms; constant trees stay constant
    key = "mu::model.layers.0.self_attn.q_proj.weight"
    assert exported[key].dtype == np.float32
    np.testing.assert_array_equal(exported[key], 0.25)
    np.testing.assert_array_equal(
        exported["nu::model.norm.weight"], 0.5
    )
    # master round-trips the weights bit-exactly (fp32)
    from neuronx_distributed_llama3_2_tpu.models.llama import params_to_hf

    want = params_to_hf(params, TINY)
    for k, v in want.items():
        np.testing.assert_array_equal(exported[f"master::{k}"], v)


def test_cli_include_optimizer_without_master(tmp_path):
    """Pure-bf16 runs (use_master_weights=False) export mu/nu only."""
    from safetensors.numpy import load_file

    from neuronx_distributed_llama3_2_tpu.checkpoint import save_checkpoint
    from neuronx_distributed_llama3_2_tpu.trainer.optimizer import (
        OptimizerState,
    )

    params = _tiny_params()
    opt = OptimizerState(
        step=jnp.asarray(3, jnp.int32),
        master=None,
        mu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
        nu=jax.tree.map(lambda p: jnp.ones(p.shape, jnp.float32), params),
    )
    ckpt = tmp_path / "native"
    save_checkpoint(str(ckpt), tag="trained", model=params, optimizer=opt)
    out = tmp_path / "hf"
    cli([
        "--direction", "native-to-hf", "--model", "tiny",
        "--input", str(ckpt), "--output", str(out), "--tag", "trained",
        "--include-optimizer",
    ])
    meta = json.loads((out / "optimizer" / "optimizer.json").read_text())
    assert meta["kinds"] == ["mu", "nu"]
    exported = load_file(str(out / "optimizer" / "optimizer.safetensors"))
    assert not any(k.startswith("master::") for k in exported)


def test_exported_config_json_loads_in_transformers():
    """config.json uses each family's real HF attribute names (review
    finding: Llama-style keys would make transformers build default-sized
    models and fail on shape mismatch)."""
    from transformers import (
        CodeGenConfig,
        DbrxConfig,
        GPTNeoXConfig,
        MixtralConfig,
    )

    from neuronx_distributed_llama3_2_tpu.models import (
        DBRX_CONFIGS,
        GPTNEOX_CONFIGS,
        MIXTRAL_CONFIGS,
    )
    from neuronx_distributed_llama3_2_tpu.scripts.checkpoint_converter import (
        _hf_config_dict,
    )

    d = _hf_config_dict(DBRX_CONFIGS["tiny-dbrx"])
    hc = DbrxConfig(**{k: v for k, v in d.items() if k != "architectures"})
    assert hc.d_model == 64 and hc.n_layers == 2 and hc.n_heads == 8
    assert hc.attn_config.kv_n_heads == 4 and hc.attn_config.clip_qkv == 8.0
    assert hc.ffn_config.moe_num_experts == 4 and hc.ffn_config.moe_top_k == 2

    d = _hf_config_dict(GPTNEOX_CONFIGS["tiny-codegen"])
    hc = CodeGenConfig(**{k: v for k, v in d.items() if k != "architectures"})
    cfg = GPTNEOX_CONFIGS["tiny-codegen"]
    assert hc.n_embd == cfg.hidden_size and hc.n_layer == cfg.num_layers
    assert hc.n_head == cfg.num_heads
    assert hc.rotary_dim == int(cfg.head_dim * cfg.rotary_pct)

    d = _hf_config_dict(GPTNEOX_CONFIGS["tiny-neox"])
    hc = GPTNeoXConfig(**{k: v for k, v in d.items() if k != "architectures"})
    cfg = GPTNEOX_CONFIGS["tiny-neox"]
    assert hc.hidden_size == cfg.hidden_size
    assert hc.rotary_pct == cfg.rotary_pct
    assert hc.use_parallel_residual == cfg.parallel_residual

    d = _hf_config_dict(MIXTRAL_CONFIGS["tiny-moe"])
    hc = MixtralConfig(**{k: v for k, v in d.items() if k != "architectures"})
    cfg = MIXTRAL_CONFIGS["tiny-moe"]
    assert hc.num_local_experts == cfg.num_experts
    assert hc.num_experts_per_tok == cfg.top_k
    assert hc.num_key_value_heads == cfg.num_kv_heads


def test_mllama_to_hf_roundtrip():
    """Vision family (beyond-reference) round-trips both directions: to_hf
    values match the HF state dict bit-exactly, and from_hf(to_hf(params))
    is the identity."""
    from tests.test_mllama import TINY as MLLAMA_TINY, _hf_tiny

    from neuronx_distributed_llama3_2_tpu.models.mllama import (
        mllama_params_from_hf,
        mllama_params_to_hf,
    )

    hf = _hf_tiny()
    sd = {
        k: v.detach().numpy().astype(np.float32)
        for k, v in hf.state_dict().items()
    }
    params = mllama_params_from_hf(sd, MLLAMA_TINY)
    back = mllama_params_to_hf(params, MLLAMA_TINY)
    assert set(back) == set(sd)  # every HF tensor exported, none extra
    for k, v in back.items():
        assert np.asarray(v).shape == np.asarray(sd[k]).shape, k
        np.testing.assert_allclose(np.asarray(v), sd[k], atol=1e-6, err_msg=k)
    again = mllama_params_from_hf(back, MLLAMA_TINY)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(again)[0],
    ):
        assert pa == pb
        np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            err_msg=str(pa),
        )


def test_mllama_config_json():
    from neuronx_distributed_llama3_2_tpu.models import MLLAMA_CONFIGS
    from neuronx_distributed_llama3_2_tpu.scripts.checkpoint_converter import (
        _hf_config_dict,
    )

    d = _hf_config_dict(MLLAMA_CONFIGS["llama3.2-11b-vision"])
    assert d["model_type"] == "mllama"
    assert d["text_config"]["num_hidden_layers"] == 40
    assert d["text_config"]["rope_scaling"]["factor"] == 8.0
    assert d["vision_config"]["max_num_tiles"] == 4


def test_mllama_vision_config_loads_in_transformers():
    """Review finding: max_aspect_ratio_id is a read-only property on HF's
    MllamaVisionConfig — the export must carry supported_aspect_ratios and
    vision_output_dim instead, and they must reproduce our derived values."""
    from transformers.models.mllama.configuration_mllama import (
        MllamaVisionConfig as HFVision,
    )

    from neuronx_distributed_llama3_2_tpu.models import MLLAMA_CONFIGS
    from neuronx_distributed_llama3_2_tpu.scripts.checkpoint_converter import (
        _hf_config_dict,
    )

    for name in ("llama3.2-11b-vision", "tiny-mllama"):
        ours = MLLAMA_CONFIGS[name].vision
        d = _hf_config_dict(MLLAMA_CONFIGS[name])["vision_config"]
        hv = HFVision(**d)
        assert hv.max_aspect_ratio_id == ours.max_aspect_ratio_id, name
        assert hv.vision_output_dim == ours.output_dim, name
        assert hv.num_global_layers == ours.num_global_layers, name


def test_cli_refuses_mllama_for_text_only_entrypoints():
    """generate.py / pretrain_llama.py give mllama keys a clean refusal
    instead of an AttributeError traceback (review finding)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "generate.py"),
         "--model", "tiny-mllama", "--prompt-ids", "1,2,3",
         "--random-init", "--cpu-devices", "2"],
        capture_output=True, text=True, timeout=240,
    )
    assert r.returncode != 0
    assert "multimodal decode needs image inputs" in (r.stderr + r.stdout)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "pretrain_llama.py"),
         "--model", "tiny-mllama", "--ckpt-dir", "/tmp/nope",
         "--synthetic", "1000", "--steps", "1", "--cpu-devices", "2"],
        capture_output=True, text=True, timeout=240,
    )
    assert r.returncode != 0
    assert "vision family needs image inputs" in (r.stderr + r.stdout)
