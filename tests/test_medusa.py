"""Medusa decoding tests (reference utils/medusa_utils.py roles): static
tree buffers, tree-attention verification, and the hard invariant — Medusa
greedy output == plain greedy decoding of the same model."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.inference.engine import (
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.inference.medusa import (
    MedusaBuffers,
    MedusaDecoder,
    MedusaHeads,
    generate_medusa_buffers,
)
from neuronx_distributed_llama3_2_tpu.models.llama import LLAMA_CONFIGS, LlamaForCausalLM

TINY = LLAMA_CONFIGS["tiny"]


# ---------------------------------------------------------------------------
# buffers (reference generate_medusa_buffers :32)
# ---------------------------------------------------------------------------

def test_buffers_structure():
    b = generate_medusa_buffers([(0,), (1,), (0, 0)], topk=4)
    # slots: root + 3 prefixes
    assert b.tree_len == 4
    assert b.depths.tolist() == [0, 1, 1, 2]
    # tree_indices: root=0(base), (0,)→1+0*4+0=1, (1,)→2, (0,0)→head1 rank0 = 1+4
    assert b.tree_indices.tolist() == [0, 1, 2, 5]
    # ancestors: (0,0) slot (3) descends from (0,) slot (1) and root
    assert b.ancestor_mask[3].tolist() == [True, True, False, True]
    # paths root→leaf
    assert b.retrieve_indices.tolist() == [[0, 1, -1], [0, 2, -1], [0, 1, 3]]


def test_buffers_reject_rank_beyond_topk():
    with pytest.raises(ValueError):
        generate_medusa_buffers([(5,)], topk=4)


# ---------------------------------------------------------------------------
# end-to-end greedy equivalence
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine():
    cfg = dataclasses.replace(TINY, loss_chunk_size=None)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.key(0))
    return InferenceEngine(cfg, params, max_batch=2, max_seq_len=128)


def test_medusa_matches_plain_greedy(engine):
    """The whole point: medusa tree decode must emit exactly the plain
    greedy continuation (acceptance is greedy-filtered)."""
    prompt = list(np.random.default_rng(0).integers(0, TINY.vocab_size, 9))
    ref = engine.generate(
        [prompt], GenerationConfig(max_new_tokens=24)
    ).sequences[0]

    heads = MedusaHeads(TINY.hidden_size, TINY.vocab_size, num_heads=3)
    mp = heads.init(jax.random.key(7))
    dec = MedusaDecoder(engine, mp, num_heads=3)
    out = dec.generate(prompt, max_new_tokens=24)
    assert out.tokens == list(ref), (out.tokens, list(ref))
    assert len(out.accepted_per_round) >= 1


def test_medusa_oracle_candidates_accept_and_stay_greedy(engine):
    """Force the multi-token acceptance path: inject the TRUE greedy
    continuation as the chain-path candidates. Rounds must accept > 0
    tokens AND the final output must still equal plain greedy — this is the
    test that catches frontier/cache off-by-ones that zero-acceptance runs
    hide (review finding)."""
    prompt = list(np.random.default_rng(1).integers(0, TINY.vocab_size, 5))
    ref = list(
        engine.generate([prompt], GenerationConfig(max_new_tokens=16)).sequences[0]
    )

    class OracleDecoder(MedusaDecoder):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.ref = ref

        def _candidates(self, base_token, medusa_logits):
            flat = super()._candidates(base_token, medusa_logits)
            # overwrite the chain path (slots sorted by depth on path 0,0,0)
            # with the true continuation of base_token
            try:
                i = self.ref.index(base_token)
            except ValueError:
                return flat
            chain = self.ref[i + 1 : i + 1 + int(self.buffers.depths.max())]
            # chain slots: the prefix path (0,), (0,0), (0,0,0) = slots where
            # tree_indices == 1 + head*topk + 0
            for d, tok in enumerate(chain, start=1):
                slot = [
                    s for s in range(self.buffers.tree_len)
                    if self.buffers.depths[s] == d
                    and self.buffers.tree_indices[s] == 1 + (d - 1) * self.buffers.topk
                ]
                if slot:
                    flat[slot[0]] = tok
            return flat

    heads = MedusaHeads(TINY.hidden_size, TINY.vocab_size, num_heads=3)
    mp = heads.init(jax.random.key(3))
    dec = OracleDecoder(engine, mp, num_heads=3)
    out = dec.generate(prompt, max_new_tokens=16)
    assert out.tokens == ref, (out.tokens, ref)
    # the oracle chain must actually get accepted at least once
    assert max(out.accepted_per_round) > 0, out.accepted_per_round


def test_medusa_cache_rows_match_plain_decode(engine):
    """After medusa generation, committed KV rows equal plain decode's for
    the same emitted sequence (direct detector for the commit off-by-one)."""
    import copy

    prompt = list(np.random.default_rng(4).integers(0, TINY.vocab_size, 6))
    heads = MedusaHeads(TINY.hidden_size, TINY.vocab_size, num_heads=3)
    mp = heads.init(jax.random.key(9))
    dec = MedusaDecoder(engine, mp, num_heads=3)
    out = dec.generate(prompt, max_new_tokens=10)
    med_cache_k = np.asarray(dec.engine.cache.k)

    # replay: prefill + sequential single-token decode of the same tokens
    full = prompt + out.tokens
    base, _, _ = dec._prefill(prompt)
    pos = len(prompt)
    for tok_pos in range(len(prompt), len(full) - 1):
        _, _, dec.engine.cache = dec._commit(
            dec.engine.params, dec.engine.cache,
            jnp.asarray([[full[tok_pos]]], jnp.int32),
            jnp.asarray([tok_pos], jnp.int32),
        )
    seq_cache_k = np.asarray(dec.engine.cache.k)
    # committed rows [0, len(full)-1) must agree
    n = len(full) - 1
    np.testing.assert_allclose(
        med_cache_k[:, 0, :n], seq_cache_k[:, 0, :n], atol=2e-5,
        err_msg="medusa-committed KV rows diverge from sequential decode",
    )


def test_tree_attention_matches_sequential(engine):
    """Verification forward with a chain tree (each node child of the
    previous) must equal the plain sequential block-causal forward."""
    eng = engine
    prompt = list(np.random.default_rng(2).integers(0, TINY.vocab_size, 7))
    heads = MedusaHeads(TINY.hidden_size, TINY.vocab_size, num_heads=3)
    dec = MedusaDecoder(
        eng, heads.init(jax.random.key(5)),
        buffers=generate_medusa_buffers([(0,), (0, 0), (0, 0, 0)], topk=2),
    )
    base, _, _ = dec._prefill(prompt)
    chain = np.asarray(
        [base, 11, 12, 13], np.int32
    )  # root + arbitrary linear chain
    depths = jnp.asarray(dec.buffers.depths)
    anc = jnp.asarray(dec.buffers.ancestor_mask)
    pos = jnp.asarray([len(prompt)], jnp.int32)

    logits_tree, _, _ = dec._fwd_hidden(
        eng.params, eng.cache, jnp.asarray(chain[None]), pos,
        tree=(depths, anc),
    )
    logits_seq, _, _ = dec._fwd_hidden(
        eng.params, eng.cache, jnp.asarray(chain[None]), pos
    )
    np.testing.assert_allclose(
        np.asarray(logits_tree), np.asarray(logits_seq), atol=2e-5, rtol=1e-5
    )
