"""TensorBoard event-writer tests (role of the reference's
NeuronTensorBoardLogger, lightning/logger.py:24): TFRecord framing with
masked crc32c, protobuf scalar encoding, crc-checked roundtrip."""

import os
import struct

import numpy as np

from neuronx_distributed_llama3_2_tpu.trainer.tensorboard import (
    TensorBoardLogger,
    _crc32c,
    _masked_crc,
    read_scalars,
)


def test_crc32c_known_vectors():
    # RFC 3720 test vectors
    assert _crc32c(b"") == 0x0
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(b"\x00" * 32) == 0x8A9136AA


def test_writer_roundtrip(tmp_path):
    logdir = tmp_path / "tb"
    with TensorBoardLogger(str(logdir)) as tb:
        for step in range(5):
            tb.log_scalars(
                step, {"train/loss": 5.0 - step * 0.5, "train/lr": 1e-4 * step}
            )
    files = os.listdir(logdir)
    assert len(files) == 1 and files[0].startswith("events.out.tfevents.")
    scalars = read_scalars(str(logdir / files[0]))
    assert set(scalars) == {"train/loss", "train/lr"}
    np.testing.assert_allclose(scalars["train/loss"][0], 5.0)
    np.testing.assert_allclose(scalars["train/loss"][4], 3.0)
    np.testing.assert_allclose(scalars["train/lr"][3], 3e-4, rtol=1e-6)


def test_file_version_header(tmp_path):
    logdir = tmp_path / "tb"
    tb = TensorBoardLogger(str(logdir))
    tb.close()
    path = logdir / os.listdir(logdir)[0]
    data = path.read_bytes()
    (length,) = struct.unpack("<Q", data[:8])
    payload = data[12 : 12 + length]
    assert b"brain.Event:2" in payload
    # framing crcs hold
    assert struct.unpack("<I", data[8:12])[0] == _masked_crc(data[:8])


def test_corruption_detected(tmp_path):
    logdir = tmp_path / "tb"
    with TensorBoardLogger(str(logdir)) as tb:
        tb.log_scalars(1, {"x": 1.0})
    path = logdir / os.listdir(logdir)[0]
    raw = bytearray(path.read_bytes())
    raw[-5] ^= 0xFF  # flip a byte inside the last record's payload
    path.write_bytes(bytes(raw))
    try:
        read_scalars(str(path))
    except ValueError as e:
        assert "crc" in str(e)
    else:
        raise AssertionError("corruption not detected")
