"""Llama model tests.

Mirrors the reference's two-tier strategy (SURVEY.md §4): numerical-parity
harness against a stock implementation with error < 1e-3
(test/integration/parallel_layers/test_layers.py:44-82 pattern; inference
accuracy gate = logits match vs HF CPU, examples/inference/runner.py:295-409),
run here on the virtual 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.parallel.layers import shard_pytree
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
    params_from_hf,
)
from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state

TINY = LLAMA_CONFIGS["tiny"]


def _hf_tiny():
    import torch
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM as HFLlama

    hf_cfg = HFLlamaConfig(
        vocab_size=TINY.vocab_size,
        hidden_size=TINY.hidden_size,
        intermediate_size=TINY.intermediate_size,
        num_hidden_layers=TINY.num_layers,
        num_attention_heads=TINY.num_heads,
        num_key_value_heads=TINY.num_kv_heads,
        head_dim=TINY.head_dim,
        max_position_embeddings=TINY.max_seq_len,
        rope_theta=TINY.rope_theta,
        rms_norm_eps=TINY.rms_norm_eps,
        tie_word_embeddings=TINY.tie_word_embeddings,
        attention_bias=False,
        mlp_bias=False,
    )
    torch.manual_seed(0)
    return HFLlama(hf_cfg).eval()


@pytest.fixture(scope="module")
def hf_model():
    return _hf_tiny()


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(1234)
    return rng.integers(0, TINY.vocab_size, size=(2, 32), dtype=np.int32)


def test_logits_match_hf(hf_model, batch):
    """Accuracy gate: our logits vs HF CPU reference (reference
    check_accuracy_logits, examples/inference/runner.py:295-409)."""
    import torch

    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(batch).long()).logits.numpy()

    model = LlamaForCausalLM(TINY)
    params = params_from_hf(hf_model.state_dict(), TINY)
    logits = jax.jit(model.__call__)(params, jnp.asarray(batch))
    np.testing.assert_allclose(
        np.asarray(logits), hf_logits, atol=1e-3, rtol=1e-3
    )


def test_loss_matches_hf(hf_model, batch):
    import torch

    ids = torch.from_numpy(batch).long()
    with torch.no_grad():
        hf_loss = hf_model(ids, labels=ids.clone()).loss.item()

    model = LlamaForCausalLM(TINY)
    params = params_from_hf(hf_model.state_dict(), TINY)
    loss = jax.jit(model.loss)(params, jnp.asarray(batch), jnp.asarray(batch))
    assert abs(float(loss) - hf_loss) < 1e-3


@pytest.mark.parametrize("sequence_parallel", [False, True])
def test_tp_matches_single_device(hf_model, batch, sequence_parallel):
    """TP=4(,SP) sharded execution is numerically identical to unsharded
    (reference parallel-vs-serial parity harness,
    test/integration/parallel_layers/test_layers.py:44-82)."""
    model = LlamaForCausalLM(TINY)
    params = params_from_hf(hf_model.state_dict(), TINY)
    ref = jax.jit(model.loss)(params, jnp.asarray(batch), jnp.asarray(batch))

    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=4, sequence_parallel=sequence_parallel
    )
    mesh = parallel_state.get_parallel_state().mesh
    sharded = shard_pytree(params, model.specs(), mesh)
    out = jax.jit(model.loss)(sharded, jnp.asarray(batch), jnp.asarray(batch))
    assert abs(float(out) - float(ref)) < 1e-4


def test_scan_equals_unrolled(hf_model, batch):
    import dataclasses

    params = params_from_hf(hf_model.state_dict(), TINY)
    scan_logits = jax.jit(LlamaForCausalLM(TINY).__call__)(
        params, jnp.asarray(batch)
    )
    unrolled = dataclasses.replace(TINY, scan_layers=False)
    unrolled_logits = jax.jit(LlamaForCausalLM(unrolled).__call__)(
        params, jnp.asarray(batch)
    )
    np.testing.assert_allclose(
        np.asarray(scan_logits), np.asarray(unrolled_logits), atol=1e-5
    )


@pytest.mark.slow
def test_remat_matches(hf_model, batch):
    import dataclasses

    params = params_from_hf(hf_model.state_dict(), TINY)
    ids = jnp.asarray(batch)
    ref = jax.jit(LlamaForCausalLM(TINY).loss)(params, ids, ids)
    for mode in ("full", "selective"):
        cfg = dataclasses.replace(TINY, remat=mode)
        model = LlamaForCausalLM(cfg)
        loss, grads = jax.jit(jax.value_and_grad(model.loss))(params, ids, ids)
        assert abs(float(loss) - float(ref)) < 1e-5
        assert all(
            bool(jnp.all(jnp.isfinite(g))) for g in jax.tree.leaves(grads)
        )


def test_rope_scaling_matches_hf(batch):
    """llama3-style rope_scaling (the published Llama-3.2 config) produces
    HF-identical logits."""
    import dataclasses

    import torch
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM as HFLlama

    cfg = dataclasses.replace(TINY, rope_scaling=(32.0, 1.0, 4.0, 16))
    hf_cfg = HFLlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_layers,
        num_attention_heads=cfg.num_heads,
        num_key_value_heads=cfg.num_kv_heads, head_dim=cfg.head_dim,
        max_position_embeddings=cfg.max_seq_len, rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_norm_eps, tie_word_embeddings=True,
        attention_bias=False, mlp_bias=False,
        rope_scaling={
            "rope_type": "llama3", "factor": 32.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 16,
        },
    )
    torch.manual_seed(0)
    hf = HFLlama(hf_cfg).eval()
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(batch).long()).logits.numpy()
    model = LlamaForCausalLM(cfg)
    params = params_from_hf(hf.state_dict(), cfg)
    logits = jax.jit(model.__call__)(params, jnp.asarray(batch))
    np.testing.assert_allclose(np.asarray(logits), hf_logits, atol=1e-3)


def test_flash_attention_path(hf_model, batch):
    """use_flash_attention=True matches the dense-attention model
    (reference nki_flash_attn_func opt-in parity)."""
    import dataclasses

    params = params_from_hf(hf_model.state_dict(), TINY)
    ids = jnp.asarray(batch)
    ref = jax.jit(LlamaForCausalLM(TINY).__call__)(params, ids)
    flash_cfg = dataclasses.replace(TINY, use_flash_attention=True)
    out = jax.jit(LlamaForCausalLM(flash_cfg).__call__)(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_init_shapes():
    model = LlamaForCausalLM(TINY)
    params = model.init(jax.random.key(0))
    specs = model.specs()
    assert jax.tree.structure(params) == jax.tree.structure(
        specs, is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec)
    )
    assert params["layers"]["mlp"]["gate_up"].shape == (
        TINY.num_layers,
        TINY.hidden_size,
        2,
        TINY.intermediate_size,
    )
