"""LoRA tests (reference test strategy: merged == base + BA parity, adapter
checkpoint roundtrip under tp — SURVEY §2.5 modules/lora + VERDICT #7)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.lora import LoraConfig, LoraModel, merge_lora
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state
from neuronx_distributed_llama3_2_tpu.parallel.layers import shard_pytree

TINY = LLAMA_CONFIGS["tiny"]


@pytest.fixture(scope="module")
def base():
    model = LlamaForCausalLM(TINY)
    return model, model.init(jax.random.key(0))


@pytest.fixture()
def batch():
    rng = np.random.default_rng(3)
    return jnp.asarray(rng.integers(0, TINY.vocab_size, (2, 16)), jnp.int32)


def test_zero_init_is_identity(base, batch):
    """B=0 at init => adapted model == base model exactly."""
    model, params = base
    lora = LoraModel(model, params, LoraConfig(r=4))
    adapters = lora.init(jax.random.key(1))
    ref = jax.jit(model.__call__)(params, batch)
    out = jax.jit(lora.__call__)(adapters, batch)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_merge_math(base):
    """merged == base + (alpha/r)·A@B on targets, untouched elsewhere."""
    model, params = base
    cfg = LoraConfig(r=4, alpha=8.0)
    lora = LoraModel(model, params, cfg)
    adapters = lora.init(jax.random.key(2))
    # nonzero B so the delta is real
    adapters = jax.tree.map(
        lambda x: x + 0.01 if x.ndim >= 2 else x, adapters
    )
    merged = merge_lora(model, params, adapters, cfg)
    q_path = adapters["layers/attn/qkv/q_kernel"]
    want = params["layers"]["attn"]["qkv"]["q_kernel"] + cfg.scaling * jnp.einsum(
        "lir,lro->lio", q_path["a"], q_path["b"]
    )
    np.testing.assert_allclose(
        np.asarray(merged["layers"]["attn"]["qkv"]["q_kernel"]),
        np.asarray(want), rtol=1e-5, atol=1e-6,
    )
    # non-target params untouched
    np.testing.assert_array_equal(
        np.asarray(merged["layers"]["mlp"]["gate_up"]),
        np.asarray(params["layers"]["mlp"]["gate_up"]),
    )


def test_rslora_scaling():
    assert LoraConfig(r=16, alpha=16.0).scaling == 1.0
    assert LoraConfig(r=16, alpha=16.0, use_rslora=True).scaling == 4.0


def test_lora_training_decreases_loss(base, batch):
    """Adapter-only training: loss decreases, base untouched, optimizer
    state is rank-sized."""
    from neuronx_distributed_llama3_2_tpu.trainer import (
        OptimizerConfig,
        TrainingConfig,
        initialize_parallel_model,
        make_train_step,
    )

    model, params = base
    lora = LoraModel(model, params, LoraConfig(r=4))
    config = TrainingConfig(
        optimizer=OptimizerConfig(
            zero_one_enabled=False, warmup_steps=1, learning_rate=5e-2
        )
    )
    config.initialize()
    state, _ = initialize_parallel_model(lora, config)
    n_opt = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(state.opt.mu))
    n_base = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    assert n_opt < n_base / 20  # adapter-sized, not model-sized
    step = make_train_step(lora, config)
    data = {"input_ids": batch, "labels": batch}
    losses = []
    for _ in range(8):
        state, m = step(state, data)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_adapter_checkpoint_roundtrip_tp2(base, batch, tmp_path):
    """Adapter-only save/load under tp=2 (reference adapter-only state_dict
    + sharded save, lora/model.py:467-616)."""
    from neuronx_distributed_llama3_2_tpu.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    model, params = base
    cfg = LoraConfig(r=4)
    parallel_state.initialize_model_parallel(tensor_model_parallel_size=2)
    mesh = parallel_state.get_parallel_state().mesh
    sharded_base = shard_pytree(params, model.specs(), mesh)
    lora = LoraModel(model, sharded_base, cfg)
    adapters = lora.init(jax.random.key(5))
    adapters = jax.tree.map(lambda x: x + 0.02, adapters)
    adapters = shard_pytree(adapters, lora.specs(), mesh)
    ref = jax.jit(lora.__call__)(adapters, batch)

    save_checkpoint(str(tmp_path), tag="adapters", model=adapters)
    loaded = load_checkpoint(
        str(tmp_path), tag="adapters",
        model=jax.eval_shape(lambda: adapters),
        model_specs=lora.specs(), mesh=mesh,
    )["model"]
    out = jax.jit(lora.__call__)(loaded, batch)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_custom_targets(base):
    model, params = base
    cfg = LoraConfig(r=2, target_modules=(r"mlp/down/kernel$", r"mlp/gate_up$"))
    lora = LoraModel(model, params, cfg)
    adapters = lora.init(jax.random.key(6))
    assert set(adapters) == {"layers/mlp/down/kernel", "layers/mlp/gate_up"}
    # fused gate_up (L, H, 2, I): B carries the (2, I) out dims
    gu = adapters["layers/mlp/gate_up"]
    assert gu["a"].shape == (TINY.num_layers, TINY.hidden_size, 2)
    assert gu["b"].shape == (TINY.num_layers, 2, 2, TINY.intermediate_size)
    with pytest.raises(ValueError, match="no parameters match"):
        LoraModel(model, params, LoraConfig(target_modules=(r"nonexistent",)))


def test_embedding_target(base, batch):
    """LoRA over the tied embedding (reference LoraEmbedding layer.py:245):
    merged = base + BA on the (V, H) table, gradients flow, base-identical
    at init."""
    model, params = base
    cfg = LoraConfig(r=4, target_modules=(r"embed/embedding$",))
    lora = LoraModel(model, params, cfg)
    adapters = lora.init(jax.random.key(1))
    assert set(adapters) == {"embed/embedding"}
    a = adapters["embed/embedding"]["a"]
    b = adapters["embed/embedding"]["b"]
    assert a.shape == (TINY.vocab_size, 4) and b.shape == (4, TINY.hidden_size)
    # zero-init identity
    np.testing.assert_allclose(
        np.asarray(lora(adapters, batch), np.float32),
        np.asarray(model(params, batch), np.float32),
        atol=1e-6,
    )
    # gradient flows into the embedding adapter
    grads = jax.grad(lora.loss)(adapters, batch, batch)
    gnorm = float(
        jnp.sum(jnp.abs(grads["embed/embedding"]["a"]))
        + jnp.sum(jnp.abs(grads["embed/embedding"]["b"]))
    )
    assert np.isfinite(gnorm) and gnorm > 0


def test_fused_gate_up_target(base, batch):
    """LoRA over the fused (L, H, 2, I) gate_up kernel: B carries the fused
    out dims (the role of the reference's fused-layer LoRA,
    LoraGQAQKVParallelLinear tp_layer.py:66)."""
    model, params = base
    cfg = LoraConfig(r=2, target_modules=(r"mlp/gate_up$",))
    lora = LoraModel(model, params, cfg)
    adapters = lora.init(jax.random.key(2))
    a = adapters["layers/mlp/gate_up"]["a"]
    b = adapters["layers/mlp/gate_up"]["b"]
    L, H, I = TINY.num_layers, TINY.hidden_size, TINY.intermediate_size
    assert a.shape == (L, H, 2)  # (stack, in, r)
    assert b.shape == (L, 2, 2, I)  # (stack, r, fused, out)
    loss0 = float(lora.loss(adapters, batch, batch))
    grads = jax.grad(lora.loss)(adapters, batch, batch)
    stepped = jax.tree.map(lambda p, g: p - 0.5 * g, adapters, grads)
    assert float(lora.loss(stepped, batch, batch)) < loss0


def test_expert_weights_refused(base):
    """5D MoE expert kernels are not LoRA-targetable; targeting them raises
    instead of silently mis-splitting the shape."""
    from neuronx_distributed_llama3_2_tpu.models import (
        MIXTRAL_CONFIGS,
        MixtralForCausalLM,
    )

    cfg = MIXTRAL_CONFIGS["tiny-moe"]
    model = MixtralForCausalLM(cfg)
    params = model.init(jax.random.key(3))
    for target in (r"experts/gate_up$", r"experts/down$"):
        with pytest.raises(ValueError, match="not LoRA-targetable"):
            LoraModel(model, params, LoraConfig(r=2, target_modules=(target,)))


# ---------------------------------------------------------------------------
# Conv2d targets (reference LoraConv2d, modules/lora/layer.py:334) + serving
# ---------------------------------------------------------------------------

def _tiny_mllama():
    from neuronx_distributed_llama3_2_tpu.models.mllama import (
        MllamaConfig,
        MllamaForConditionalGeneration,
        MllamaTextConfig,
        MllamaVisionConfig,
    )

    cfg = MllamaConfig(
        vision=MllamaVisionConfig(
            hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_global_layers=1, attention_heads=2, image_size=28,
            patch_size=14, max_num_tiles=2, max_aspect_ratio_id=3,
            intermediate_layers_indices=(0, 1),
        ),
        text=MllamaTextConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_heads=4, num_kv_heads=2,
            cross_attention_layers=(1,), rope_theta=10000.0, max_seq_len=64,
        ),
    )
    return MllamaForConditionalGeneration(cfg)


def test_conv_target_vision_lora_parity():
    """Vision LoRA on the Mllama patch conv: merged kernel == base +
    scale·(A ⊛ B) with A carrying the spatial kernel and B the 1×1 mix —
    the reference LoraConv2d factorization (layer.py:334)."""
    model = _tiny_mllama()
    params = model.init(jax.random.key(0))
    cfg = LoraConfig(
        r=4,
        alpha=8.0,
        target_modules=(r"layers/plain/attn/qkv/q_kernel$",),
        conv_target_modules=(r"vision_model/patch_embedding/kernel$",),
    )
    lm = LoraModel(model, params, cfg)
    adapters = lm.init(jax.random.key(1))
    conv_path = next(p for p in adapters if "patch_embedding" in p)
    kh, kw, cin, cout = 14, 14, 3, 32
    assert adapters[conv_path]["a"].shape == (kh, kw, cin, 4)
    assert adapters[conv_path]["b"].shape == (4, cout)

    # B = 0 ⇒ merged == base exactly
    merged0 = lm.merged_params(adapters)
    base_kernel = params["vision_model"]["patch_embedding"]["kernel"]
    np.testing.assert_array_equal(
        np.asarray(merged0["vision_model"]["patch_embedding"]["kernel"]),
        np.asarray(base_kernel),
    )

    # non-zero B ⇒ merged == base + scaling·einsum(hwir,ro)
    adapters[conv_path]["b"] = (
        jax.random.normal(jax.random.key(2), (4, cout), jnp.float32) * 0.1
    ).astype(adapters[conv_path]["b"].dtype)
    merged = lm.merged_params(adapters)
    want = np.asarray(base_kernel, np.float32) + cfg.scaling * np.einsum(
        "hwir,ro->hwio",
        np.asarray(adapters[conv_path]["a"], np.float32),
        np.asarray(adapters[conv_path]["b"], np.float32),
    )
    np.testing.assert_allclose(
        np.asarray(merged["vision_model"]["patch_embedding"]["kernel"]),
        want, atol=1e-5, rtol=1e-5,
    )
    # the q-kernel linear target coexists with the conv target
    assert any("attn/qkv/q_kernel" in p for p in adapters)


def test_conv_target_requires_rank4():
    model = LlamaForCausalLM(TINY)
    params = model.init(jax.random.key(0))
    with pytest.raises(ValueError, match="rank-4"):
        LoraModel(
            model, params,
            LoraConfig(
                target_modules=(r"qkv/q_kernel$",),
                conv_target_modules=(r"attn/o/kernel$",),
            ),
        )


def test_conv_and_linear_pattern_overlap_refused():
    model = _tiny_mllama()
    params = model.init(jax.random.key(0))
    with pytest.raises(ValueError, match="both"):
        LoraModel(
            model, params,
            LoraConfig(
                target_modules=(r"patch_embedding/kernel$",),
                conv_target_modules=(r"patch_embedding/kernel$",),
            ),
        )


def test_decode_with_merged_lora_adapters(base):
    """Serving merged-LoRA params (reference merge-for-inference flow,
    lora/model.py:357): zero-B adapters decode identically to the base;
    trained (non-zero) adapters change the output stream."""
    from neuronx_distributed_llama3_2_tpu.inference.engine import (
        GenerationConfig,
        InferenceEngine,
    )
    from neuronx_distributed_llama3_2_tpu.inference.sampling import (
        SamplingConfig,
    )
    from neuronx_distributed_llama3_2_tpu.lora.model import merge_lora

    model, params = base
    cfg = LoraConfig(r=4, alpha=8.0)
    lm = LoraModel(model, params, cfg)
    adapters = lm.init(jax.random.key(3))
    gen = GenerationConfig(
        max_new_tokens=8, sampling=SamplingConfig(greedy=True)
    )
    prompt = list(range(1, 9))

    ref = InferenceEngine(TINY, params, max_batch=1, max_seq_len=64).generate(
        [prompt], gen
    ).sequences[0]
    merged0 = merge_lora(model, params, adapters, cfg)
    got0 = InferenceEngine(TINY, merged0, max_batch=1, max_seq_len=64).generate(
        [prompt], gen
    ).sequences[0]
    assert got0 == ref  # B=0: adapters are exactly inert in serving

    # non-trivial adapters flow through the decode path
    adapters = jax.tree.map(
        lambda x: jax.random.normal(jax.random.key(5), x.shape, jnp.float32)
        .astype(x.dtype) * 0.3,
        adapters,
    )
    merged1 = merge_lora(model, params, adapters, cfg)
    got1 = InferenceEngine(TINY, merged1, max_batch=1, max_seq_len=64).generate(
        [prompt], gen
    ).sequences[0]
    assert got1 != ref
