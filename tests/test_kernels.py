"""Kernel tests: flash attention (jnp blockwise + pallas interpret mode) and
the chunked fused CE. Parity gates mirror the reference's kernel test
tolerances (flash attn vs CoreAttention; test/integration parity <1e-3)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.kernels.flash_attention import (
    flash_attention_reference,
)
from neuronx_distributed_llama3_2_tpu.kernels.pallas_flash_attention import (
    pallas_flash_attention,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
    core_attention,
)

TINY = LLAMA_CONFIGS["tiny"]


def _qkv(s=200, n=4, nkv=2, d=64, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((1, s, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, s, nkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, s, nkv, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_jnp_flash_matches_dense(causal):
    q, k, v = _qkv()
    ref = core_attention(q, k, v, causal=causal)
    out = flash_attention_reference(q, k, v, causal=causal, block_kv=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_jnp_flash_segments():
    q, k, v = _qkv(s=128)
    seg = jnp.concatenate(
        [jnp.zeros((1, 64), jnp.int32), jnp.ones((1, 64), jnp.int32)], axis=1
    )
    out = flash_attention_reference(q, k, v, segment_ids=seg, block_kv=32)
    # first token of doc 2 attends only itself
    expect = jnp.repeat(v, 2, axis=2)[:, 64]
    np.testing.assert_allclose(np.asarray(out[:, 64]), np.asarray(expect), atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_interpret_matches_dense(causal):
    """Pallas kernels in interpreter mode (TPU lowering exercised by bench on
    the real chip)."""
    q, k, v = _qkv()
    ref = core_attention(q, k, v, causal=causal)
    out = pallas_flash_attention(q, k, v, causal=causal, block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_backward_matches_dense():
    q, k, v = _qkv()

    def lp(q, k, v):
        return (pallas_flash_attention(q, k, v, block_q=128, block_kv=128) ** 2).sum()

    def lr(q, k, v):
        return (core_attention(q, k, v) ** 2).sum()

    gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_pallas_unaligned_seq():
    """No seq%2048 constraint (the NKI kernel requires it, flash_attn.py:178)."""
    q, k, v = _qkv(s=173)
    ref = core_attention(q, k, v, causal=True)
    out = pallas_flash_attention(q, k, v, block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_segments_match_reference(causal):
    """Packed-document masking in-kernel (VERDICT #9): Pallas path parity
    with the jnp segment implementation, unaligned doc boundaries."""
    q, k, v = _qkv(s=200)
    seg = jnp.asarray(
        np.repeat([0, 1, 2], [70, 60, 70])[None, :], jnp.int32
    )
    ref = flash_attention_reference(
        q, k, v, causal=causal, segment_ids=seg, block_kv=64
    )
    out = pallas_flash_attention(
        q, k, v, causal=causal, segment_ids=seg, block_q=128, block_kv=128
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_segments_backward():
    q, k, v = _qkv(s=128)
    seg = jnp.concatenate(
        [jnp.zeros((1, 64), jnp.int32), jnp.ones((1, 64), jnp.int32)], axis=1
    )

    def lp(q, k, v):
        return (
            pallas_flash_attention(
                q, k, v, segment_ids=seg, block_q=64, block_kv=64
            ) ** 2
        ).sum()

    def lr(q, k, v):
        return (
            flash_attention_reference(q, k, v, segment_ids=seg, block_kv=64) ** 2
        ).sum()

    gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


@pytest.mark.slow  # tier-1 time budget; cheaper siblings cover this path
def test_chunked_ce_matches_full():
    model = LlamaForCausalLM(TINY)
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, 256, (4, 50)), jnp.int32
    )
    labels = ids.at[:, ::7].set(-100)  # sprinkle ignore-index
    ref_l, ref_g = jax.value_and_grad(model.loss)(params, ids, labels)
    chunked = LlamaForCausalLM(dataclasses.replace(TINY, loss_chunk_size=16))
    l2, g2 = jax.value_and_grad(chunked.loss)(params, ids, labels)
    assert abs(float(ref_l) - float(l2)) < 1e-5
    for a, b in zip(jax.tree.leaves(ref_g), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


# ---------------------------------------------------------------------------
# paged flash-decode kernel (gather-free block-table attention)
# ---------------------------------------------------------------------------

from neuronx_distributed_llama3_2_tpu.kernels.paged_attention_pallas import (  # noqa: E402
    paged_flash_decode,
)


def _paged_decode_ref(q, kp, vp, tables, positions, kv_limit):
    """Dense-gather reference: materialize the K/V rows through the table
    (exactly what the kernel must avoid), grouped GQA masked softmax."""
    nb, bs, nkv, d = kp.shape
    jlog = jnp.arange(kv_limit)
    phys = tables[:, jlog // bs] * bs + (jlog % bs)[None, :]
    k_all = kp.reshape(nb * bs, nkv, d)[phys]  # (b, limit, NKV, D)
    v_all = vp.reshape(nb * bs, nkv, d)[phys]
    g = q.shape[1] // nkv
    qg = q.reshape(q.shape[0], nkv, g, d)
    sc = jnp.einsum("bskd,bkgd->bkgs", k_all, qg) * (d ** -0.5)
    mask = (
        jnp.arange(kv_limit)[None, None, None, :]
        <= positions[:, None, None, None]
    )
    sc = jnp.where(mask, sc, -jnp.inf)
    p = jax.nn.softmax(sc, axis=-1)
    return jnp.einsum("bkgs,bskd->bkgd", p, v_all).reshape(q.shape)


def _paged_pool(b, n, nkv, d, nb, bs, w, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, n, d)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((nb, bs, nkv, d)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((nb, bs, nkv, d)), jnp.float32)
    # shuffled non-null pool blocks per lane: exercises real indirection
    tables = jnp.asarray(
        np.stack([rng.permutation(np.arange(1, nb))[:w] for _ in range(b)]),
        jnp.int32,
    )
    return q, kp, vp, tables


@pytest.mark.parametrize("num_splits", [1, 2, 4])
def test_paged_decode_matches_gather_reference(num_splits):
    """Flash-decoding split-K over block tables == dense-gather softmax, for
    any split count (the LSE combine must be exact)."""
    b, n, nkv, d, nb, bs, w = 3, 4, 2, 8, 16, 8, 8
    kv_limit = 64
    q, kp, vp, tables = _paged_pool(b, n, nkv, d, nb, bs, w)
    # positions hitting: block start, mid-block (partial last block), last row
    positions = jnp.asarray([0, 17, 63], jnp.int32)
    ref = _paged_decode_ref(q, kp, vp, tables, positions, kv_limit)
    out = paged_flash_decode(
        q, kp, vp, tables, positions, kv_limit=kv_limit, num_splits=num_splits
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_paged_decode_gqa_groups_and_kv_limit():
    """GQA grouping (G=4) and a kv_limit that is not a multiple of the
    split count: padding blocks past nblk must contribute nothing."""
    b, n, nkv, d, nb, bs, w = 2, 8, 2, 8, 24, 8, 12
    kv_limit = 40  # 5 blocks, split 4 ways -> 2 blocks/split, 3 padded
    q, kp, vp, tables = _paged_pool(b, n, nkv, d, nb, bs, w, seed=7)
    positions = jnp.asarray([39, 8], jnp.int32)
    ref = _paged_decode_ref(q, kp, vp, tables, positions, kv_limit)
    out = paged_flash_decode(
        q, kp, vp, tables, positions, kv_limit=kv_limit, num_splits=4
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_paged_decode_masks_garbage_blocks():
    """Rows past a lane's position are masked whatever the table points at:
    aliasing every later entry to a garbage-filled block must not change the
    output (the null-block invariant the serving engine relies on)."""
    b, n, nkv, d, nb, bs, w = 2, 4, 2, 8, 16, 8, 8
    kv_limit = 64
    q, kp, vp, tables = _paged_pool(b, n, nkv, d, nb, bs, w, seed=3)
    positions = jnp.asarray([11, 20], jnp.int32)
    out = paged_flash_decode(q, kp, vp, tables, positions, kv_limit=kv_limit)
    # frontier block index per lane is 1 and 2; alias everything after it
    aliased = np.asarray(tables).copy()
    aliased[0, 2:] = 15
    aliased[1, 3:] = 15
    out2 = paged_flash_decode(
        q, kp, vp, jnp.asarray(aliased), positions, kv_limit=kv_limit
    )
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out), atol=1e-6)


def test_paged_decode_bf16_pool_fp32_query():
    """cache_dtype=bf16 pool under an fp32 query: the kernel casts K/V to
    the query dtype in-register, like the gather path's .astype."""
    b, n, nkv, d, nb, bs, w = 2, 4, 2, 8, 16, 8, 8
    q, kp, vp, tables = _paged_pool(b, n, nkv, d, nb, bs, w, seed=5)
    positions = jnp.asarray([30, 61], jnp.int32)
    ref = _paged_decode_ref(
        q, kp.astype(jnp.bfloat16).astype(jnp.float32),
        vp.astype(jnp.bfloat16).astype(jnp.float32), tables, positions, 64,
    )
    out = paged_flash_decode(
        q, kp.astype(jnp.bfloat16), vp.astype(jnp.bfloat16), tables,
        positions, kv_limit=64,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def _paged_decode_ref_mt(q, kp, vp, tables, positions, kv_limit):
    """Multi-token dense-gather reference: query token ti of lane i sits at
    row positions[i] + ti and attends rows <= positions[i] + ti (the dense
    path's block-causal ``j <= position + t``)."""
    nb, bs, nkv, d = kp.shape
    b, t, n, _ = q.shape
    jlog = jnp.arange(kv_limit)
    phys = tables[:, jlog // bs] * bs + (jlog % bs)[None, :]
    k_all = kp.reshape(nb * bs, nkv, d)[phys]  # (b, limit, NKV, D)
    v_all = vp.reshape(nb * bs, nkv, d)[phys]
    g = n // nkv
    qg = q.reshape(b, t, nkv, g, d)
    sc = jnp.einsum("bskd,btkgd->btkgs", k_all, qg) * (d ** -0.5)
    mask = (
        jlog[None, None, :]
        <= positions[:, None, None] + jnp.arange(t)[None, :, None]
    )  # (b, t, limit)
    sc = jnp.where(mask[:, :, None, None, :], sc, -jnp.inf)
    p = jax.nn.softmax(sc, axis=-1)
    return jnp.einsum("btkgs,bskd->btkgd", p, v_all).reshape(q.shape)


@pytest.mark.parametrize("kv_limit", [64, 128, 256])
@pytest.mark.parametrize("t", [2, 4, 8])
def test_paged_decode_multi_token_matches_reference(t, kv_limit):
    """Speculative-verify geometry: a linear fresh block of t tokens folded
    into the query tile must match the block-causal dense gather for every
    (t, kv_limit) the serving verify path can dispatch."""
    b, n, nkv, d, nb, bs, w = 3, 4, 2, 8, 48, 8, 32
    rng = np.random.default_rng(100 + t)
    _, kp, vp, tables = _paged_pool(b, n, nkv, d, nb, bs, w, seed=t)
    q = jnp.asarray(rng.standard_normal((b, t, n, d)), jnp.float32)
    # first-fresh-token rows hitting block start / mid-block / near the end
    positions = jnp.asarray(
        [0, (kv_limit // 2) + 1, kv_limit - t], jnp.int32
    )
    ref = _paged_decode_ref_mt(q, kp, vp, tables, positions, kv_limit)
    for num_splits in (1, 4):
        out = paged_flash_decode(
            q, kp, vp, tables, positions,
            kv_limit=kv_limit, num_splits=num_splits,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_paged_decode_t1_four_dim_equals_three_dim():
    """The 4-dim (b, 1, N, D) entry point is exactly the legacy 3-dim call:
    same kernel, same mask, shape-only difference."""
    b, n, nkv, d, nb, bs, w = 2, 4, 2, 8, 16, 8, 8
    q, kp, vp, tables = _paged_pool(b, n, nkv, d, nb, bs, w, seed=9)
    positions = jnp.asarray([5, 50], jnp.int32)
    out3 = paged_flash_decode(q, kp, vp, tables, positions, kv_limit=64)
    out4 = paged_flash_decode(
        q[:, None], kp, vp, tables, positions, kv_limit=64
    )
    assert out4.shape == (b, 1, n, d)
    np.testing.assert_allclose(
        np.asarray(out4[:, 0]), np.asarray(out3), atol=1e-6
    )
