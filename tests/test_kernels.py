"""Kernel tests: flash attention (jnp blockwise + pallas interpret mode) and
the chunked fused CE. Parity gates mirror the reference's kernel test
tolerances (flash attn vs CoreAttention; test/integration parity <1e-3)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.kernels.flash_attention import (
    flash_attention_reference,
)
from neuronx_distributed_llama3_2_tpu.kernels.pallas_flash_attention import (
    pallas_flash_attention,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
    core_attention,
)

TINY = LLAMA_CONFIGS["tiny"]


def _qkv(s=200, n=4, nkv=2, d=64, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((1, s, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, s, nkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, s, nkv, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_jnp_flash_matches_dense(causal):
    q, k, v = _qkv()
    ref = core_attention(q, k, v, causal=causal)
    out = flash_attention_reference(q, k, v, causal=causal, block_kv=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_jnp_flash_segments():
    q, k, v = _qkv(s=128)
    seg = jnp.concatenate(
        [jnp.zeros((1, 64), jnp.int32), jnp.ones((1, 64), jnp.int32)], axis=1
    )
    out = flash_attention_reference(q, k, v, segment_ids=seg, block_kv=32)
    # first token of doc 2 attends only itself
    expect = jnp.repeat(v, 2, axis=2)[:, 64]
    np.testing.assert_allclose(np.asarray(out[:, 64]), np.asarray(expect), atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_interpret_matches_dense(causal):
    """Pallas kernels in interpreter mode (TPU lowering exercised by bench on
    the real chip)."""
    q, k, v = _qkv()
    ref = core_attention(q, k, v, causal=causal)
    out = pallas_flash_attention(q, k, v, causal=causal, block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_backward_matches_dense():
    q, k, v = _qkv()

    def lp(q, k, v):
        return (pallas_flash_attention(q, k, v, block_q=128, block_kv=128) ** 2).sum()

    def lr(q, k, v):
        return (core_attention(q, k, v) ** 2).sum()

    gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_pallas_unaligned_seq():
    """No seq%2048 constraint (the NKI kernel requires it, flash_attn.py:178)."""
    q, k, v = _qkv(s=173)
    ref = core_attention(q, k, v, causal=True)
    out = pallas_flash_attention(q, k, v, block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_segments_match_reference(causal):
    """Packed-document masking in-kernel (VERDICT #9): Pallas path parity
    with the jnp segment implementation, unaligned doc boundaries."""
    q, k, v = _qkv(s=200)
    seg = jnp.asarray(
        np.repeat([0, 1, 2], [70, 60, 70])[None, :], jnp.int32
    )
    ref = flash_attention_reference(
        q, k, v, causal=causal, segment_ids=seg, block_kv=64
    )
    out = pallas_flash_attention(
        q, k, v, causal=causal, segment_ids=seg, block_q=128, block_kv=128
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_segments_backward():
    q, k, v = _qkv(s=128)
    seg = jnp.concatenate(
        [jnp.zeros((1, 64), jnp.int32), jnp.ones((1, 64), jnp.int32)], axis=1
    )

    def lp(q, k, v):
        return (
            pallas_flash_attention(
                q, k, v, segment_ids=seg, block_q=64, block_kv=64
            ) ** 2
        ).sum()

    def lr(q, k, v):
        return (
            flash_attention_reference(q, k, v, segment_ids=seg, block_kv=64) ** 2
        ).sum()

    gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_chunked_ce_matches_full():
    model = LlamaForCausalLM(TINY)
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, 256, (4, 50)), jnp.int32
    )
    labels = ids.at[:, ::7].set(-100)  # sprinkle ignore-index
    ref_l, ref_g = jax.value_and_grad(model.loss)(params, ids, labels)
    chunked = LlamaForCausalLM(dataclasses.replace(TINY, loss_chunk_size=16))
    l2, g2 = jax.value_and_grad(chunked.loss)(params, ids, labels)
    assert abs(float(ref_l) - float(l2)) < 1e-5
    for a, b in zip(jax.tree.leaves(ref_g), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
