"""TP-sharded paged serving: tp=2 CPU-hosted parity, residency, jaxpr.

The contract under test (docs/serving.md "Multi-chip serving"): on a pure
tensor-parallel mesh whose tp divides both head counts, the paged decode
path stays on the Pallas kernel — run per rank inside a shard_map region on
its NKV head slice (``paged_flash_decode_tp``) — and must be

- token-identical to the tp=1 engine (and the dense engine) for greedy
  sampling across the spec × {sync,async} × {chunked,whole} matrix,
- still gather-free: the decode jaxpr under the mesh contains no
  ``(b, kv_limit, NKV, D)`` materialized K/V copy,
- still resident: the async steady state does zero host→device uploads
  with readback lag exactly 1, tables/positions replicated.

The mesh is CPU-hosted: conftest forces 8 virtual devices, and
``initialize_model_parallel(..., devices=jax.devices()[:2])`` makes the
mesh pure-tp (without the explicit slice the spare devices would land on
dp and the eligibility gate would — correctly — fall back to the gather).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.inference import (
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.parallel.state import (
    initialize_model_parallel,
)
from neuronx_distributed_llama3_2_tpu.serving import (
    PagedConfig,
    PagedServingEngine,
)

from tests.test_async_serving import _paged, _run
from tests.test_paged_serving import _dense_outputs, _prompts

TINY = LLAMA_CONFIGS["tiny"]
# tiny: num_heads=8, num_kv_heads=4 — both divide tp=2 (2 kv heads/rank)
TINY_KERNEL = dataclasses.replace(TINY, use_paged_kernel=True)


@pytest.fixture(scope="module")
def params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


def _tp_mesh(tp=2):
    """Pure-tp mesh over the first ``tp`` virtual CPU devices."""
    return initialize_model_parallel(
        tensor_model_parallel_size=tp, devices=jax.devices()[:tp]
    )


# -- eligibility gate ------------------------------------------------------


def test_kernel_gate_tp_divisible_mesh():
    """tp=2 pure mesh with divisible heads: kernel eligible for the whole
    linear-t range AND for packed-tree verify — the per-lane ancestor
    bitmasks ride into the shard_map region replicated like the block
    tables, so trees cost no new collectives; only a >32-node tree
    (ancestor sets no longer pack into int32) falls back to the gather."""
    from neuronx_distributed_llama3_2_tpu.inference.model import LlamaDecode

    _tp_mesh()
    m = LlamaDecode(TINY_KERNEL)
    assert m._paged_kernel_eligible(1, None)
    assert m._paged_kernel_eligible(TINY.paged_kernel_max_t, None)
    assert not m._paged_kernel_eligible(TINY.paged_kernel_max_t + 1, None)
    assert m._paged_kernel_eligible(TINY.paged_kernel_max_t, object())
    wide = LlamaDecode(
        dataclasses.replace(TINY_KERNEL, paged_kernel_max_t=64)
    )
    assert wide._paged_kernel_eligible(33, None)
    assert not wide._paged_kernel_eligible(33, object())  # int32 bound


def test_kernel_gate_indivisible_heads_fall_back():
    """nkv % tp != 0 means the pool replicated (paged_cache_specs'
    _head_axis fallback) — the gate must keep the sharded gather."""
    from neuronx_distributed_llama3_2_tpu.inference.model import LlamaDecode

    _tp_mesh()
    odd = dataclasses.replace(TINY_KERNEL, num_heads=6, num_kv_heads=3)
    assert not LlamaDecode(odd)._paged_kernel_eligible(1, None)


def test_kernel_gate_non_tp_mesh_falls_back():
    """A dp-extended mesh (8 devices, tp=2 ⇒ dp=4) is not pure-tp: the
    head-split shard_map region would not cover the mesh, so the gate
    falls back to the sharded einsums."""
    from neuronx_distributed_llama3_2_tpu.inference.model import LlamaDecode
    from neuronx_distributed_llama3_2_tpu.parallel.state import mesh_is_tp_only

    initialize_model_parallel(tensor_model_parallel_size=2)  # all 8 devices
    assert not mesh_is_tp_only()
    assert not LlamaDecode(TINY_KERNEL)._paged_kernel_eligible(1, None)


def test_kernel_gate_size_one_mesh_still_eligible():
    """A tp=1 single-device mesh is the single-chip case — eligible."""
    from neuronx_distributed_llama3_2_tpu.inference.model import LlamaDecode

    initialize_model_parallel(devices=jax.devices()[:1])
    assert LlamaDecode(TINY_KERNEL)._paged_kernel_eligible(1, None)


def test_mesh_is_tp_only_uninitialized_is_false():
    from neuronx_distributed_llama3_2_tpu.parallel.state import mesh_is_tp_only

    assert not mesh_is_tp_only()


# -- sharded kernel unit parity -------------------------------------------


@pytest.mark.parametrize("t", [None, 1, 4], ids=["3dim", "t1", "t4"])
def test_sharded_kernel_matches_single_chip(t):
    """paged_flash_decode_tp on a tp=2 mesh == paged_flash_decode on one
    chip, bitwise (same kernel body, disjoint head slices, fp32)."""
    from neuronx_distributed_llama3_2_tpu.kernels.paged_attention_pallas import (
        paged_flash_decode,
        paged_flash_decode_tp,
    )

    b, n, nkv, d, nb, bs, w, limit = 3, 8, 4, 16, 17, 8, 6, 40
    tt = 1 if t is None else t
    rng = np.random.default_rng(5)
    qshape = (b, n, d) if t is None else (b, t, n, d)
    q = jnp.asarray(rng.normal(size=qshape), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(nb, bs, nkv, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(nb, bs, nkv, d)), jnp.float32)
    nblk = -(-limit // bs)
    perm = rng.permutation(np.arange(1, nb))
    tables = np.zeros((b, w), np.int32)
    for i in range(b):
        tables[i, :nblk] = perm[i * nblk:(i + 1) * nblk]
    tables = jnp.asarray(tables)
    pos = jnp.asarray(rng.integers(0, limit - tt + 1, size=(b,)), jnp.int32)

    ref = jax.jit(
        lambda q, k, v: paged_flash_decode(q, k, v, tables, pos, kv_limit=limit)
    )(q, kp, vp)
    st = _tp_mesh()
    out = jax.jit(
        lambda q, k, v: paged_flash_decode_tp(
            q, k, v, tables, pos, mesh=st.mesh, kv_limit=limit
        )
    )(q, kp, vp)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sharded_kernel_rejects_indivisible_heads():
    from neuronx_distributed_llama3_2_tpu.kernels.paged_attention_pallas import (
        paged_flash_decode_tp,
    )

    st = _tp_mesh()
    q = jnp.zeros((2, 6, 8), jnp.float32)
    pool = jnp.zeros((4, 8, 3, 8), jnp.float32)  # nkv=3, tp=2
    with pytest.raises(ValueError, match="divide tp"):
        paged_flash_decode_tp(
            q, pool, pool, jnp.zeros((2, 2), jnp.int32),
            jnp.zeros((2,), jnp.int32), mesh=st.mesh,
        )


# -- engine parity matrix --------------------------------------------------


MATRIX_GEN = GenerationConfig(max_new_tokens=8)


@pytest.fixture(scope="module")
def matrix_ref(params):
    """(prompts, dense outputs) — identical across every matrix cell, so
    computed once per module (the matrix only varies scheduling knobs)."""
    rng = np.random.default_rng(7)
    # repetitive + free-text mix so the spec variants actually accept drafts
    pat = rng.integers(1, TINY.vocab_size, size=3).tolist()
    prompts = [(pat * 7)[:18]] + _prompts(rng, (5, 20, 9))
    return prompts, _dense_outputs(params, prompts, MATRIX_GEN)


# tier-1 time budget: the default tier runs a trio covering every
# chunk/async/spec value and most pairs (whole-sync-spec completes the
# pairwise quartet from the slow tier); the rest of the cube also rides
# in the slow tier.
@pytest.mark.parametrize(
    "chunk,async_loop,spec",
    [
        pytest.param(6, True, 3, id="chunked-async-spec"),
        pytest.param(6, False, 0, id="chunked-sync-plain"),
        pytest.param(None, True, 0, id="whole-async-plain"),
        pytest.param(None, False, 3, id="whole-sync-spec",
                     marks=pytest.mark.slow),
        pytest.param(6, False, 3, id="chunked-sync-spec",
                     marks=pytest.mark.slow),
        pytest.param(6, True, 0, id="chunked-async-plain",
                     marks=pytest.mark.slow),
        pytest.param(None, True, 3, id="whole-async-spec",
                     marks=pytest.mark.slow),
        pytest.param(None, False, 0, id="whole-sync-plain",
                     marks=pytest.mark.slow),
    ],
)
def test_tp2_engine_parity_matrix(params, matrix_ref, chunk, async_loop, spec):
    """Greedy outputs identical: tp=2 engine == tp=1 engine == dense engine,
    across speculative × async-loop × chunked-prefill, with the Pallas
    kernel eligible (no dense-gather fallback) on both sides."""
    gen = MATRIX_GEN
    prompts, ref = matrix_ref
    cfg = dict(
        block_size=8, num_blocks=64, prefill_chunk_tokens=chunk,
        async_loop=async_loop, spec_draft_tokens=spec,
    )
    p1 = _paged(params, gen, PagedConfig(**cfg), TINY_KERNEL)
    assert p1.model._paged_kernel_eligible(1, None)
    out_tp1 = _run(p1, prompts)
    _tp_mesh()
    p2 = _paged(params, gen, PagedConfig(**cfg), TINY_KERNEL)
    assert p2.model._paged_kernel_eligible(1, None), "tp=2 must not fall back"
    out_tp2 = _run(p2, prompts)
    assert out_tp2 == out_tp1
    assert out_tp2 == ref
    m = p2.metrics
    assert m.tp_size == 2
    if spec:
        assert m.verify_steps > 0 and m.accepted_tokens > 0
    if async_loop and not spec:
        # with spec on, verify steps run sync and this short well-drafting
        # workload may never re-enter the lookahead — plain cells must
        assert m.decode_steps_async > 0


# -- residency + jaxpr under the mesh --------------------------------------


def test_tp2_steady_state_is_fully_resident(params):
    """PR 4's acceptance check survives the mesh: replicated resident
    tables/positions mean a steady-state async step still uploads nothing
    and its readback lags dispatch by exactly one step."""
    _tp_mesh()
    gen = GenerationConfig(max_new_tokens=24)
    paged = _paged(
        params, gen,
        PagedConfig(block_size=32, num_blocks=8, async_loop=True),
        TINY_KERNEL,
    )
    paged.submit(_prompts(np.random.default_rng(0), (4,))[0])
    paged.step()  # admission + prefill
    paged.step()  # first async dispatch flushes the dirty lane
    m = paged.metrics
    for _ in range(12):
        before = (m.h2d_uploads, m.lane_syncs, m.table_deltas)
        assert paged.step()
        assert (m.h2d_uploads, m.lane_syncs, m.table_deltas) == before
        assert paged._last_readback_lag == 1
    paged.run_to_completion()


def test_tp2_decode_jaxpr_has_no_gather(params):
    """Under the tp=2 mesh the kernel-path decode jaxpr must still not
    materialize the (b, kv_limit, NKV, D) gathered K/V copy — neither at
    full NKV nor at the per-rank NKV/tp slice — while the gather-path
    jaxpr (use_paged_kernel off) does contain its sharded gather."""
    from neuronx_distributed_llama3_2_tpu.analysis.graftcheck import all_shapes
    from neuronx_distributed_llama3_2_tpu.inference.model import LlamaDecode

    b, kv_limit, nb, bs, w = 4, 32, 16, 8, 8

    _tp_mesh()
    nkv = TINY.num_kv_heads
    forbidden = {
        (b, kv_limit, nkv, TINY.head_dim),          # full gather
        (b, kv_limit, nkv // 2, TINY.head_dim),     # per-rank gather
    }
    for flag, expect_gather in ((False, True), (True, False)):
        cfg = dataclasses.replace(TINY, use_paged_kernel=flag)
        model = LlamaDecode(cfg)
        cache = model.init_paged_cache(nb, bs)
        closed = jax.make_jaxpr(
            lambda p, c, t, ps, tb: model.forward(  # noqa: B023
                p, c, t, ps, None, block_tables=tb, kv_limit=kv_limit
            )
        )(
            params, cache, jnp.zeros((b, 1), jnp.int32),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b, w), jnp.int32),
        )
        shapes = all_shapes(closed)
        hit = bool(forbidden & shapes)
        assert hit is expect_gather, (
            f"use_paged_kernel={flag}: gather aval "
            f"{'missing' if expect_gather else 'present'} in tp decode jaxpr"
        )


# -- pool sizing / metrics -------------------------------------------------


def test_pool_bytes_per_rank_arithmetic():
    from neuronx_distributed_llama3_2_tpu.serving.block_allocator import (
        kv_pool_bytes_per_rank,
    )

    dims = dict(
        num_layers=4, num_blocks=64, block_size=8, num_kv_heads=4,
        head_dim=8, dtype_bytes=4,
    )
    total = kv_pool_bytes_per_rank(**dims)
    assert total == 2 * 4 * 64 * 8 * 4 * 8 * 4
    # divisible: the tp× aggregate-capacity identity
    assert kv_pool_bytes_per_rank(**dims, tp_size=2) * 2 == total
    # non-divisible heads replicate: per-rank bytes do not shrink
    odd = dict(dims, num_kv_heads=3)
    assert kv_pool_bytes_per_rank(**odd, tp_size=2) == kv_pool_bytes_per_rank(**odd)


def test_tp_rows_in_metrics_snapshot(params):
    """tp_size and the pool-byte rows land in snapshot(); at tp=2 the
    per-rank bytes are exactly half the logical pool."""
    gen = GenerationConfig(max_new_tokens=4)
    p1 = _paged(params, gen, PagedConfig(block_size=8, num_blocks=32), TINY_KERNEL)
    snap1 = p1.metrics.snapshot(p1.allocator, p1.index)
    assert snap1["tp_size"] == 1
    assert snap1["pool_bytes_per_rank"] == snap1["pool_bytes_total"] > 0
    _tp_mesh()
    p2 = _paged(params, gen, PagedConfig(block_size=8, num_blocks=32), TINY_KERNEL)
    snap2 = p2.metrics.snapshot(p2.allocator, p2.index)
    assert snap2["tp_size"] == 2
    assert snap2["pool_bytes_total"] == snap1["pool_bytes_total"]
    assert snap2["pool_bytes_per_rank"] * 2 == snap2["pool_bytes_total"]
