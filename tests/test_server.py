"""graftserve front door: asyncio server, HTTP transport, SLO scheduler.

Tier-1 gate for the streaming server (serving/server.py) and the
SLO-aware step policy (serving/scheduler.py), entirely on the tiny CPU
engine:

- concurrent asyncio clients stream token-identical outputs to the batch
  ``run_to_completion`` path (the stream is fed by the same readback);
- the hand-rolled HTTP transport round-trips completions (plain + SSE),
  request lookup, cancel, and both scrape endpoints;
- a prewarmed SloPolicy engine holds the zero-upload steady state and
  ``steadystate_compiles == 0`` — scheduling authority lives entirely in
  host-side action meta, so the device path must be byte-identical;
- the ``scripts/serving_load.py --smoke`` leg runs in-process, which is
  where the fifo-vs-slo acceptance comparison (interactive p99 TTFT
  improves, tokens/step within 5%) is enforced.

All runs finish with the invariant auditor, the block-pool leak check,
and the GC010 schedule automaton clean.
"""

import asyncio
import importlib.util
import json
import os

import jax
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.inference import (
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.analysis.graftsched import (
    check_action_trace,
)
from neuronx_distributed_llama3_2_tpu.serving import (
    GraftServer,
    PagedConfig,
    PagedServingEngine,
    audit_engine,
)
from neuronx_distributed_llama3_2_tpu.serving.policy import make_policy
from neuronx_distributed_llama3_2_tpu.serving.scheduler import SloPolicy

from tests.test_paged_serving import _prompts

TINY = LLAMA_CONFIGS["tiny"]

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "scripts")


@pytest.fixture(scope="module")
def params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


def _paged(params, gen, paged_cfg, **engine_kw):
    engine_kw.setdefault("max_batch", 4)
    engine_kw.setdefault("max_seq_len", 64)
    engine_kw.setdefault("buckets", [8, 16, 32])
    eng = InferenceEngine(TINY, params, **engine_kw)
    return PagedServingEngine(eng, gen, paged_cfg)


def _audit(eng):
    assert eng._pending is None
    assert eng.allocator.active_blocks == 0
    assert eng.allocator.leak_check() == []
    assert audit_engine(eng) == []
    assert check_action_trace(eng) == []


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_slo_policy_registered():
    """``step_policy="slo"`` resolves through the registry: scheduler.py
    is imported lazily by make_policy, so configs name it as a string."""
    pol = make_policy("slo")
    assert isinstance(pol, SloPolicy)
    assert pol.name == "slo"


def test_streamed_tokens_match_batch_run(params):
    """Concurrent streaming clients receive exactly the tokens the batch
    path commits, responses carry terminal timing + usage, and no stream
    is left open."""
    gen = GenerationConfig(max_new_tokens=6)
    cfg = dict(
        block_size=8, num_blocks=64, prefill_chunk_tokens=8,
        async_loop=True, step_policy="slo",
    )
    prompts = _prompts(np.random.default_rng(7), (5, 12, 20, 9, 17))

    batch = _paged(params, gen, PagedConfig(**cfg))
    for p in prompts:
        batch.submit(p)
    expected = batch.run_to_completion()

    eng = _paged(params, gen, PagedConfig(**cfg))
    got = {}
    responses = {}

    async def client(srv, i, prompt):
        sc = "interactive" if i % 2 else "batch"
        rid = srv.submit(prompt, service_class=sc, tenant=f"t{i % 2}")
        toks = []
        async for t in srv.stream(rid):
            toks.append(t)
        got[rid] = toks
        responses[rid] = srv.response(rid)

    async def main():
        async with GraftServer(eng, idle_poll_s=0.002) as srv:
            await asyncio.gather(*(
                client(srv, i, p) for i, p in enumerate(prompts)
            ))
            return srv.snapshot()

    snap = asyncio.run(main())
    assert got == expected
    for rid, resp in responses.items():
        assert resp["status"] == "finished"
        assert resp["choices"][0]["token_ids"] == expected[rid]
        assert resp["choices"][0]["finish_reason"] in ("length", "stop")
        assert resp["error"] is None
        assert resp["usage"]["completion_tokens"] == len(expected[rid])
        assert resp["usage"]["prompt_tokens"] == len(prompts[rid])
        assert resp["timing"]["ttft_ms"] is not None
    assert snap["active_streams"] == 0
    assert snap["finished"] == len(prompts)
    assert snap["requests_by_class"]["interactive"]["finished"] == 2
    assert snap["requests_by_class"]["batch"]["finished"] == 3
    _audit(eng)


def test_cancel_mid_stream(params):
    """A client cancel mid-decode closes the stream, yields a structured
    ``cancelled`` error payload, and leaves the survivor token-identical
    to an uncancelled engine's output for the same rid."""
    gen = GenerationConfig(max_new_tokens=12)
    cfg = dict(block_size=8, num_blocks=64, async_loop=True)
    prompts = _prompts(np.random.default_rng(9), (6, 10))

    solo = _paged(params, gen, PagedConfig(**cfg))
    for p in prompts:
        solo.submit(p)
    baseline = solo.run_to_completion()

    eng = _paged(params, gen, PagedConfig(**cfg))

    async def main():
        async with GraftServer(eng, idle_poll_s=0.002) as srv:
            victim = srv.submit(prompts[0])
            survivor = srv.submit(prompts[1])

            async def stream_victim():
                toks = []
                async for t in srv.stream(victim):
                    toks.append(t)
                    if len(toks) == 2:
                        assert srv.cancel(victim) is True
                return toks

            async def stream_survivor():
                return [t async for t in srv.stream(survivor)]

            v_toks, s_toks = await asyncio.gather(
                stream_victim(), stream_survivor()
            )
            # cancel is idempotent once terminal
            assert srv.cancel(victim) is False
            return v_toks, s_toks, srv.response(victim), srv.snapshot()

    v_toks, s_toks, v_resp, snap = asyncio.run(main())
    assert s_toks == baseline[1]  # survivor untouched by the cancel
    assert v_toks == baseline[0][: len(v_toks)]  # prefix of the full run
    assert len(v_toks) < len(baseline[0])
    assert v_resp["status"] == "failed"
    assert v_resp["error"]["type"] == "cancelled"
    assert v_resp["choices"][0]["finish_reason"] == "cancelled"
    assert snap["cancelled_requests"] == 1
    assert snap["active_streams"] == 0
    _audit(eng)


def test_http_transport_roundtrips(params):
    """The stdlib HTTP loop: plain + SSE completions, request lookup,
    cancel route, scrape endpoints, and 404s — one in-process socket
    client per request (``Connection: close`` framing)."""
    gen = GenerationConfig(max_new_tokens=5)
    eng = _paged(
        params, gen, PagedConfig(block_size=8, num_blocks=64, async_loop=True)
    )
    prompt = _prompts(np.random.default_rng(4), (7,))[0]

    async def http(host, port, method, target, body=None):
        reader, writer = await asyncio.open_connection(host, port)
        payload = b"" if body is None else json.dumps(body).encode()
        writer.write(
            f"{method} {target} HTTP/1.1\r\nHost: {host}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
        )
        await writer.drain()
        raw = await reader.read()
        writer.close()
        await writer.wait_closed()
        head, _, data = raw.partition(b"\r\n\r\n")
        return int(head.split()[1]), data

    async def main():
        srv = GraftServer(eng, idle_poll_s=0.002)
        host, port = await srv.serve_http()
        try:
            status, data = await http(
                host, port, "POST", "/v1/completions",
                {"prompt": prompt, "service_class": "interactive",
                 "tenant": "acme"},
            )
            assert status == 200
            resp = json.loads(data)
            assert resp["status"] == "finished"
            assert resp["service_class"] == "interactive"
            assert resp["tenant"] == "acme"
            first = resp["choices"][0]["token_ids"]
            assert len(first) == gen.max_new_tokens

            # SSE: same prompt, token events must equal the final payload
            status, data = await http(
                host, port, "POST", "/v1/completions",
                {"prompt": prompt, "stream": True},
            )
            assert status == 200
            events = [
                json.loads(line[len("data: "):])
                for line in data.decode().split("\n\n")
                if line.startswith("data: ") and line != "data: [DONE]"
            ]
            assert "data: [DONE]" in data.decode()
            toks = [e["token"] for e in events if "token" in e]
            final = [e for e in events if "choices" in e][-1]
            assert final["choices"][0]["token_ids"] == toks
            assert toks == first  # greedy determinism across requests

            status, data = await http(host, port, "GET", "/v1/requests/0")
            assert status == 200
            assert json.loads(data)["id"] == "cmpl-0"

            # cancel on an already-finished rid: 200, cancelled=false
            status, data = await http(
                host, port, "POST", "/v1/requests/0/cancel"
            )
            assert status == 200
            assert json.loads(data) == {"rid": 0, "cancelled": False}

            status, _ = await http(host, port, "GET", "/v1/requests/99")
            assert status == 404
            status, _ = await http(
                host, port, "POST", "/v1/requests/99/cancel"
            )
            assert status == 404
            status, _ = await http(host, port, "GET", "/nope")
            assert status == 404

            status, data = await http(host, port, "GET", "/snapshot")
            assert status == 200
            snap = json.loads(data)
            assert snap["finished"] == 2
            assert "requests_by_class" in snap

            status, data = await http(host, port, "GET", "/metrics")
            assert status == 200
            text = data.decode()
            assert "serving_finished 2" in text
            assert 'serving_info{kv_dtype="' in text
            assert 'serving_requests_class{class="interactive"' in text
        finally:
            await srv.close()

    asyncio.run(main())
    _audit(eng)


def test_slo_steady_state_resident_under_prewarm(params):
    """SloPolicy must not tax the device path: on a prewarmed async
    engine, steady-state decode steps do zero host→device uploads and the
    whole run compiles nothing after the prewarm freeze
    (``steadystate_compiles == 0``) — scheduling lives in action meta,
    which the device programs never see."""
    gen = GenerationConfig(max_new_tokens=24)
    paged = _paged(
        params, gen,
        PagedConfig(
            block_size=32, num_blocks=8, async_loop=True, prewarm=True,
            step_policy="slo",
            slo_ttft_p99_ms=50.0, slo_tpot_p99_ms=10_000.0,
            slo_eval_steps=8,
        ),
    )
    paged.submit(
        _prompts(np.random.default_rng(0), (4,))[0],
        service_class="interactive", tenant="acme",
    )
    paged.step()  # admission + prefill
    paged.step()  # first async dispatch flushes the dirty lane
    m = paged.metrics
    for _ in range(12):
        before = (m.h2d_uploads, m.lane_syncs, m.table_deltas)
        assert paged.step()
        assert (m.h2d_uploads, m.lane_syncs, m.table_deltas) == before
    paged.run_to_completion()
    assert m.prewarm_compiles > 0
    assert m.steadystate_compiles == 0
    _audit(paged)


def test_serving_load_smoke_in_process(params):
    """The load harness's tier-1 leg: burst fifo-vs-slo comparison (the
    interactive-p99-improves / throughput-within-5% acceptance gates) and
    the async streaming-client leg, sharing the suite's compile cache."""
    mod = _load_script("serving_load")
    assert mod.main(["--smoke"]) == 0
