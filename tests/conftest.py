"""Test config: force an 8-device virtual CPU backend before jax import.

Mirrors the reference's hardware-free unit-test tier (SURVEY.md §4): schedules
and partition logic test pure; distributed numerics test on a multi-device CPU
mesh (the analogue of the reference's mocked process groups +
single-XLA-device golden comparisons, test/unit_test/...).
"""

import os

import jax

# jax may already be imported by the environment's sitecustomize with a TPU
# backend registered; config.update (not env vars) is the reliable override.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no num_cpu_devices config; the XLA flag is the
    # equivalent as long as it lands before the backend initializes
    # (importing jax alone does not initialize it)
    flags = [
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append("--xla_force_host_platform_device_count=8")
    os.environ["XLA_FLAGS"] = " ".join(flags)
jax.config.update("jax_threefry_partitionable", True)

# Persistent XLA compile cache: the suite is compile-dominated (hundreds of
# tiny jit programs, identical across runs), and warm-cache runs cut wall
# time several-fold (measured 1.3s -> 0.18s per program). Keyed by HLO +
# compile options, so staleness is not a correctness risk; disable with
# NXDT_TEST_COMPILE_CACHE=0 for a cold-compile tier. The cpu_aot_loader
# "machine feature +prefer-no-scatter" E-spam on cache hits is an XLA
# tuning-flag-vs-CPUID cosmetic mismatch, captured away by pytest.
if os.environ.get("NXDT_TEST_COMPILE_CACHE", "1") != "0":
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(
            "NXDT_TEST_COMPILE_CACHE_DIR",
            os.path.join(os.path.dirname(__file__), ".jax_cache"),
        ),
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402

from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    yield
    parallel_state.destroy_model_parallel()
