"""Test config: force an 8-device virtual CPU backend before jax import.

Mirrors the reference's hardware-free unit-test tier (SURVEY.md §4): schedules
and partition logic test pure; distributed numerics test on a multi-device CPU
mesh (the analogue of the reference's mocked process groups +
single-XLA-device golden comparisons, test/unit_test/...).
"""

import jax

# jax may already be imported by the environment's sitecustomize with a TPU
# backend registered; config.update (not env vars) is the reliable override.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402

from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    yield
    parallel_state.destroy_model_parallel()
