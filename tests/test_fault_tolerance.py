"""Fault tolerance for the paged serving engine: chaos injection, failure
domains, invariant audit, degradation ladder, stall watchdog.

The contract under test (docs/serving.md "Failure handling & degradation"):
a fault — injected device error, NaN logits, drafter bug, transient alloc
failure, transfer latency — aborts only the affected request(s). Every
other lane's greedy output stays **token-identical** to a fault-free run
of the same workload (per-lane attention independence), the block pool
drains clean, and the invariant auditor finds nothing. Faulted requests
surface terminally as ``status == "failed"`` with the error detail, and
their partial output is a prefix of the fault-free output (greedy
determinism: every token committed before the fault was a valid token).

The chaos soak at the bottom is the acceptance check: a seeded randomized
arrival schedule with every feature on (async lookahead, speculation,
chunked prefill, tight pool) and every fault class firing, driven twice
to prove bit-reproducibility of the chaos run itself.
"""

import dataclasses

import jax
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.analysis.graftcheck import audit_programs
from neuronx_distributed_llama3_2_tpu.inference import (
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.serving import (
    AllocatorError,
    BlockAllocator,
    EngineStalledError,
    FaultInjector,
    FaultPlan,
    InvariantViolation,
    PagedConfig,
    PagedServingEngine,
    audit_engine,
    make_serving_engine,
)

from tests.test_paged_serving import _prompts

TINY = LLAMA_CONFIGS["tiny"]
TINY_KERNEL = dataclasses.replace(TINY, use_paged_kernel=True)


@pytest.fixture(scope="module")
def params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


# InferenceEngine is read-only under the paged engine (all serving state —
# pool, tables, programs — lives on PagedServingEngine), so tests share one
# instance per model config; lazy compile keeps each test paying only for
# the program variants it actually dispatches
_ENGINES = {}


def _paged(params, gen, paged_cfg, model_cfg=TINY, injector=None,
           precompile=False, drafter=None, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("buckets", [8, 16, 32])
    key = (id(model_cfg), kw["max_batch"], kw["max_seq_len"],
           tuple(kw["buckets"]))
    if key not in _ENGINES:
        _ENGINES[key] = InferenceEngine(model_cfg, params, **kw)
    return PagedServingEngine(
        _ENGINES[key], gen, paged_cfg, precompile=precompile,
        injector=injector, drafter=drafter,
    )


def _run(paged, prompts):
    for p in prompts:
        paged.submit(p)
    return paged.run_to_completion()


# shared workloads + configs: the per-fault-class tests all compare against
# a fault-free reference run, so pinning (config, workload) pairs lets one
# baseline drive serve several fault classes (cached below)
GEN10 = GenerationConfig(max_new_tokens=10)
CFG_PLAIN = PagedConfig(block_size=8, num_blocks=64)
CFG_ASYNC = PagedConfig(block_size=8, num_blocks=64, async_loop=True)
CFG_SPEC = PagedConfig(block_size=8, num_blocks=64, spec_draft_tokens=4)
PLAIN_PROMPTS = _prompts(np.random.default_rng(3), (5, 12, 20, 9))
_rep_rng = np.random.default_rng(6)
# repetitive prompts so speculative configs actually draft/verify
REP_PROMPTS = [
    (_rep_rng.integers(1, 9, size=3).tolist() * 8)[:n] for n in (9, 12, 15)
]

_BASELINES = {}


def _baseline(params, gen, cfg, prompts):
    key = (cfg, tuple(tuple(p) for p in prompts), gen.max_new_tokens)
    if key not in _BASELINES:
        _BASELINES[key] = _run(_paged(params, gen, cfg), prompts)
    return _BASELINES[key]


def _statuses(paged):
    return {rid: paged.request_info(rid)["status"] for rid in paged._requests}


def _assert_clean_pool(paged):
    assert paged._pending is None
    assert paged.allocator.active_blocks == 0
    assert paged.allocator.leak_check() == []
    assert audit_engine(paged) == []
    assert audit_programs(paged) == []


def _assert_survivor_parity(paged, baseline):
    """Finished requests match the fault-free run exactly; failed requests
    carry error detail and a prefix of the fault-free output."""
    n_finished = n_failed = 0
    for rid, req in paged._finished.items():
        info = paged.request_info(rid)
        if info["status"] == "failed":
            n_failed += 1
            assert info["error"]
            assert req.out == baseline[rid][: len(req.out)]
        else:
            n_finished += 1
            assert info["status"] == "finished"
            assert info["error"] is None
            assert req.out == baseline[rid]
    return n_finished, n_failed


# ---------------------------------------------------------------------------
# injector units: determinism, schedules, plan validation
# ---------------------------------------------------------------------------


def test_injector_is_deterministic():
    plan = FaultPlan(seed=5, device_rate=0.3, nan_rate=0.2, alloc_rate=0.1)

    def drive(inj):
        for step in range(30):
            inj.begin_step(step)
            inj.device_fault("decode", [0, 1, 2, 3])
            inj.nan_lanes("decode", [0, 1])
            inj.alloc_fault()
        return list(inj.fired)

    assert drive(FaultInjector(plan)) == drive(FaultInjector(plan))
    assert FaultInjector(plan).total_fired == 0  # nothing until consulted


def test_injector_schedule_fires_exactly_once():
    inj = FaultInjector(FaultPlan(schedule=((3, "device"), (3, "drafter"))))
    assert inj.wants("device") and inj.wants("drafter")
    assert not inj.wants("nan")
    for step in range(10):
        inj.begin_step(step)
        inj.device_fault("decode", [0, 1])
        try:
            inj.drafter_fault()
        except RuntimeError:
            pass
    # each entry fired at the first opportunity at/after its step, once
    assert inj.counts["device"] == 1 and inj.counts["drafter"] == 1
    assert [f[0] for f in inj.fired] == [3, 3]


def test_fault_plan_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan(schedule=((0, "gremlin"),))


def test_make_serving_engine_rejects_injector_without_paged(params):
    eng = InferenceEngine(TINY, params, max_batch=2, max_seq_len=32)
    with pytest.raises(ValueError, match="paged"):
        make_serving_engine(eng, injector=FaultInjector(FaultPlan()))


# ---------------------------------------------------------------------------
# allocator: typed errors + leak detection
# ---------------------------------------------------------------------------


def test_allocator_double_release_is_typed():
    a = BlockAllocator(num_blocks=8, block_size=4)
    b = a.alloc()
    a.release(b)
    with pytest.raises(AllocatorError, match="double release") as ei:
        a.release(b)
    assert ei.value.bid == b and ei.value.op == "release"


def test_allocator_incref_after_free_is_typed():
    a = BlockAllocator(num_blocks=8, block_size=4)
    b = a.alloc()
    a.release(b)
    with pytest.raises(AllocatorError, match="not allocated") as ei:
        a.incref(b)
    assert ei.value.bid == b and ei.value.op == "incref"


def test_allocator_leak_check_flags_corruption():
    a = BlockAllocator(num_blocks=8, block_size=4)
    held = a.alloc()
    assert a.leak_check() == []
    # simulate a leak: a registered block also sitting on the free list
    a._free.append(held)
    assert held in a.leak_check()
    a._free.pop()
    assert a.leak_check() == []
    a.release(held)


def test_allocator_fault_hook_reports_transient_exhaustion():
    a = BlockAllocator(num_blocks=8, block_size=4)
    fires = iter([True, False])
    a.fault_hook = lambda: next(fires)
    assert a.alloc() is None          # injected exhaustion, pool untouched
    assert a.free_blocks == 7
    b = a.alloc()                     # next call succeeds normally
    assert b is not None
    a.release(b)
    assert a.leak_check() == []


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------


def test_stall_watchdog_names_stuck_work(params):
    gen = GenerationConfig(max_new_tokens=4)
    paged = _paged(
        params, gen,
        PagedConfig(block_size=8, num_blocks=32, stall_step_limit=3),
        precompile=False,
    )
    paged.submit([1, 2, 3])
    paged._free_lanes.clear()  # wedge: queued work, no lane can ever open
    with pytest.raises(EngineStalledError) as ei:
        for _ in range(10):
            paged.step()
    assert ei.value.limit == 3
    assert ei.value.queued == [0]
    assert "no progress for 3" in str(ei.value)


def test_watchdog_tolerates_slow_but_progressing_steps(params):
    # latency faults on every transfer must not trip the watchdog: slow
    # steps still make progress, and progress is what the watchdog counts
    gen = GenerationConfig(max_new_tokens=6)
    inj = FaultInjector(FaultPlan(latency_rate=1.0, latency_ms=0.1))
    paged = _paged(
        params, gen,
        PagedConfig(block_size=8, num_blocks=32, stall_step_limit=2),
        injector=inj,
    )
    out = _run(paged, _prompts(np.random.default_rng(0), (5, 9)))
    assert len(out) == 2
    assert inj.counts["latency"] > 0
    _assert_clean_pool(paged)


# ---------------------------------------------------------------------------
# failure domains: one lane dies, the rest are untouched
# ---------------------------------------------------------------------------


def test_prefill_fault_fails_only_the_admitting_request(params):
    baseline = _baseline(params, GEN10, CFG_PLAIN, PLAIN_PROMPTS)

    inj = FaultInjector(FaultPlan(schedule=((0, "device"),)))
    paged = _paged(params, GEN10, CFG_PLAIN, injector=inj)
    _run(paged, PLAIN_PROMPTS)
    assert inj.counts["device"] == 1
    assert inj.fired[0][2] == "prefill"  # fired at the admission funnel
    n_finished, n_failed = _assert_survivor_parity(paged, baseline)
    assert (n_finished, n_failed) == (3, 1)
    assert paged.metrics.failed_requests == 1
    _assert_clean_pool(paged)


@pytest.mark.parametrize("async_loop", [False, True], ids=["sync", "async"])
def test_decode_fault_fails_one_lane_others_identical(params, async_loop):
    cfg = CFG_ASYNC if async_loop else CFG_PLAIN
    baseline = _baseline(params, GEN10, cfg, PLAIN_PROMPTS)

    inj = FaultInjector(FaultPlan(seed=2, schedule=((6, "device"),)))
    paged = _paged(params, GEN10, cfg, injector=inj)
    _run(paged, PLAIN_PROMPTS)
    assert inj.counts["device"] == 1
    n_finished, n_failed = _assert_survivor_parity(paged, baseline)
    assert (n_finished, n_failed) == (3, 1)
    assert paged.metrics.faults_injected == 1
    _assert_clean_pool(paged)


@pytest.mark.parametrize("cfg", [CFG_ASYNC, CFG_SPEC], ids=["async", "spec"])
def test_nan_quarantine_fails_the_poisoned_lane(params, cfg):
    baseline = _baseline(params, GEN10, cfg, REP_PROMPTS)

    inj = FaultInjector(FaultPlan(seed=3, schedule=((5, "nan"),)))
    paged = _paged(params, GEN10, cfg, injector=inj)
    assert paged._check_logits  # nan plan implies checked programs
    _run(paged, REP_PROMPTS)
    assert inj.counts["nan"] == 1
    assert paged.metrics.lane_quarantines == 1
    n_finished, n_failed = _assert_survivor_parity(paged, baseline)
    assert (n_finished, n_failed) == (2, 1)
    failed = [r for r in paged._finished.values() if r.failed]
    assert "non-finite" in failed[0].error
    _assert_clean_pool(paged)


CFG_FUSED = PagedConfig(
    block_size=8, num_blocks=64, prefill_chunk_tokens=6, fused_step=True,
)


@pytest.mark.parametrize("kind", ["device", "nan"])
def test_fused_step_fault_fails_one_lane_others_identical(params, kind):
    """The failure domain of a fused mixed-mode dispatch is still ONE
    lane: even though prefill-chunk, verify, and decode rows ride a
    single pmixed program, a fault at its funnel aborts only the chosen
    victim, and every survivor stays token-identical to the fault-free
    UNFUSED run — failure-domain parity and fused token parity pinned by
    the same assertion."""
    baseline = _baseline(
        params, GEN10, dataclasses.replace(CFG_FUSED, fused_step=False),
        PLAIN_PROMPTS,
    )
    # step 2: rid 2 (len 20, chunk 6) is still mid-chunk-walk, so the
    # first device/nan opportunity at or after it is the mixed funnel
    inj = FaultInjector(FaultPlan(seed=4, schedule=((2, kind),)))
    paged = _paged(params, GEN10, CFG_FUSED, injector=inj)
    _run(paged, PLAIN_PROMPTS)
    assert inj.counts[kind] == 1
    assert inj.fired[0][2] == "mixed"  # fired at the fused dispatch
    assert paged.metrics.mixed_dispatches > 0
    if kind == "nan":
        assert paged._check_logits  # nan plan implies checked pmixed
        assert paged.metrics.lane_quarantines == 1
    n_finished, n_failed = _assert_survivor_parity(paged, baseline)
    assert (n_finished, n_failed) == (3, 1)
    assert paged.metrics.failed_requests == 1
    _assert_clean_pool(paged)


def test_detect_nonfinite_clean_run_changes_nothing(params):
    # checked programs with healthy logits: finite everywhere, no
    # quarantines, outputs identical to the unchecked engine
    baseline = _baseline(params, GEN10, CFG_ASYNC, PLAIN_PROMPTS)
    paged = _paged(
        params, GEN10, dataclasses.replace(CFG_ASYNC, detect_nonfinite=True)
    )
    assert paged._check_logits
    assert _run(paged, PLAIN_PROMPTS) == baseline
    assert paged.metrics.lane_quarantines == 0
    _assert_clean_pool(paged)


def test_drafter_fault_is_absorbed_without_failing_requests(params):
    baseline = _baseline(params, GEN10, CFG_SPEC, REP_PROMPTS)

    inj = FaultInjector(FaultPlan(seed=9, drafter_rate=0.5))
    paged = _paged(params, GEN10, CFG_SPEC, injector=inj)
    assert _run(paged, REP_PROMPTS) == baseline  # drafting is advisory
    assert inj.counts["drafter"] > 0
    assert paged.metrics.drafter_faults == inj.counts["drafter"]
    assert paged.metrics.failed_requests == 0
    _assert_clean_pool(paged)


def test_real_drafter_exception_is_absorbed_too(params):
    # the failure contract covers genuine drafter bugs, not just chaos
    class BuggyDrafter:
        def propose(self, history, max_tokens):
            raise ZeroDivisionError("drafter bug")

    baseline = _baseline(params, GEN10, CFG_SPEC, REP_PROMPTS)
    paged = _paged(params, GEN10, CFG_SPEC, drafter=BuggyDrafter())
    assert _run(paged, REP_PROMPTS) == baseline
    assert paged.metrics.drafter_faults > 0
    assert paged.metrics.failed_requests == 0


def test_alloc_fault_causes_backoff_not_failure(params):
    baseline = _baseline(params, GEN10, CFG_PLAIN, PLAIN_PROMPTS)

    inj = FaultInjector(FaultPlan(seed=12, alloc_rate=0.25))
    paged = _paged(params, GEN10, CFG_PLAIN, injector=inj)
    # transient exhaustion surfaces as the normal no-block path (admission
    # back-off / preempt-requeue); greedy recompute keeps outputs identical
    assert _run(paged, PLAIN_PROMPTS) == baseline
    assert inj.counts["alloc"] > 0
    assert paged.metrics.failed_requests == 0
    _assert_clean_pool(paged)


# ---------------------------------------------------------------------------
# request lifecycle: status + error surfacing
# ---------------------------------------------------------------------------


def test_request_info_status_lifecycle(params):
    gen = GenerationConfig(max_new_tokens=12)
    paged = _paged(
        params, gen,
        PagedConfig(
            block_size=4, num_blocks=10, decode_reserve_blocks=1,
            prefill_chunk_tokens=4,
        ),
    )
    for p in _prompts(np.random.default_rng(13), (14, 14, 12)):
        paged.submit(p)
    seen = set(_statuses(paged).values())
    assert seen == {"queued"}
    alive, steps = True, 0
    while alive:
        alive = paged.step()
        steps += 1
        seen |= set(_statuses(paged).values())
        assert steps < 500
    # the tight pool + chunked prefill walked every non-failure state
    assert {"queued", "prefilling", "active", "preempted", "finished"} <= seen
    assert set(_statuses(paged).values()) == {"finished"}
    for rid in paged._requests:
        assert paged.request_info(rid)["error"] is None
    _assert_clean_pool(paged)


# ---------------------------------------------------------------------------
# invariant auditor
# ---------------------------------------------------------------------------


def test_auditor_passes_mid_flight_and_detects_corruption(params):
    gen = GenerationConfig(max_new_tokens=16)
    paged = _paged(
        params, gen, PagedConfig(block_size=8, num_blocks=64, audit_interval=2)
    )
    for p in _prompts(np.random.default_rng(14), (5, 12, 9)):
        paged.submit(p)
    for _ in range(4):
        paged.step()
    assert audit_engine(paged) == []       # clean engine, mid-decode
    assert paged.metrics.audit_violations == 0

    req = next(iter(paged._active.values()))
    bid = req.table[0]
    paged.allocator._ref[bid] += 1         # corrupt: phantom reference
    violations = audit_engine(paged)
    assert any(f"block {bid}" in s for s in violations)
    with pytest.raises(InvariantViolation):
        paged._audit(strict=True)
    assert paged.metrics.audit_violations > 0

    paged.allocator._ref[bid] -= 1         # restore and drain clean
    assert audit_engine(paged) == []
    paged.run_to_completion()
    _assert_clean_pool(paged)


def test_periodic_audit_counts_violations_without_raising(params):
    gen = GenerationConfig(max_new_tokens=8)
    paged = _paged(
        params, gen, PagedConfig(block_size=8, num_blocks=64, audit_interval=1)
    )
    paged.submit(_prompts(np.random.default_rng(15), (6,))[0])
    paged.step()
    req = next(iter(paged._active.values()))
    paged.allocator._ref[req.table[0]] += 1
    paged.step()                           # periodic audit: logs + counts
    assert paged.metrics.audit_violations > 0
    paged.allocator._ref[req.table[0]] -= 1
    paged.run_to_completion()


# ---------------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------------


def test_degradation_ladder_climbs_and_recovers(params):
    gen = GenerationConfig(max_new_tokens=24)
    prompts = _prompts(np.random.default_rng(16), (5, 12, 9, 17, 6, 11, 8, 14))
    cfg = PagedConfig(
        block_size=8, num_blocks=64, async_loop=True,
        degrade_after_faults=1, degrade_window_steps=16,
        degrade_recover_steps=4,
    )
    baseline = _run(_paged(params, gen, dataclasses.replace(
        cfg, degrade_after_faults=0), TINY_KERNEL), prompts)

    inj = FaultInjector(
        FaultPlan(seed=17, schedule=((4, "device"), (7, "device"), (10, "device")))
    )
    paged = _paged(params, gen, cfg, TINY_KERNEL, injector=inj)
    for p in prompts:
        paged.submit(p)
    levels = []
    while paged.step():
        levels.append(paged._degrade_level)
        assert len(levels) < 1000
    # three events, one rung each: spec shed -> async shed -> kernel shed
    assert max(levels) == 3
    assert paged.metrics.degradations == 3
    # rung 3 actually recompiled onto the gather fallback...
    assert any(k[0] == "pdecode" and k[3] for k in paged._programs)
    # ...and clean windows stepped all the way back down
    assert paged._degrade_level == 0
    assert paged.metrics.degradation_level == 0
    assert not paged._gather_shed()
    n_finished, n_failed = _assert_survivor_parity(paged, baseline)
    assert n_failed == 3 and n_finished == 5
    _assert_clean_pool(paged)


def test_ladder_off_by_default_under_faults(params):
    gen = GenerationConfig(max_new_tokens=8)
    prompts = _prompts(np.random.default_rng(18), (5, 9))
    inj = FaultInjector(FaultPlan(schedule=((3, "device"),)))
    paged = _paged(
        params, gen, PagedConfig(block_size=8, num_blocks=64), injector=inj
    )
    _run(paged, prompts)
    assert paged.metrics.degradations == 0
    assert paged._degrade_level == 0


# ---------------------------------------------------------------------------
# fault-free purity: no injector, no behavior change
# ---------------------------------------------------------------------------


def test_fault_free_engine_builds_no_checked_or_gather_programs(params):
    gen = GenerationConfig(max_new_tokens=8)
    paged = _paged(params, gen, PagedConfig(block_size=8, num_blocks=64))
    _run(paged, _prompts(np.random.default_rng(19), (5, 12)))
    assert paged.injector is None
    assert paged._check_logits is False
    assert paged._zero_mask is None        # the nan-mask cache never built
    for key in paged._programs:
        if key[0] == "pdecode":
            assert key[3] is False and key[4] is False  # gather, checked
        assert key[0] != "pverify"
    m = paged.metrics
    assert m.faults_injected == 0
    assert m.failed_requests == 0
    assert m.lane_quarantines == 0
    assert m.degradation_level == 0
    assert m.audit_violations == 0


# ---------------------------------------------------------------------------
# the chaos soak: everything on, every fault class, reproducible
# ---------------------------------------------------------------------------


def _chaos_soak(params, n_requests, arrival_span, max_new, plan, workload_seed,
                repeat_chaos=False):
    rng = np.random.default_rng(workload_seed)
    gen = GenerationConfig(max_new_tokens=max_new)
    # chaos drives run prewarmed: after the catalog freeze the only legal
    # mid-traffic compiles are the degradation ladder's gather twins
    # (exempt from steadystate_compiles / GC008)
    cfg = PagedConfig(
        block_size=4, num_blocks=24, decode_reserve_blocks=1,
        prefill_chunk_tokens=8, async_loop=True, spec_draft_tokens=4,
        stall_step_limit=300, audit_interval=8, audit_debug=True,
        degrade_after_faults=3, degrade_window_steps=32,
        degrade_recover_steps=16, prewarm=True,
    )
    lengths = rng.integers(3, 32, size=n_requests)
    prompts = []
    for i, n in enumerate(lengths):
        if i % 2 == 0:  # repetitive half so speculation engages
            pat = rng.integers(1, 9, size=3).tolist()
            prompts.append((pat * (int(n) // 3 + 1))[: int(n)])
        else:
            prompts.append(
                rng.integers(0, TINY.vocab_size, size=(int(n),)).tolist()
            )
    arrivals = np.sort(rng.integers(0, arrival_span, size=n_requests)).tolist()

    def drive(injector):
        paged = _paged(
            params, gen,
            cfg if injector is not None
            else dataclasses.replace(
                cfg, audit_interval=0, audit_debug=False, prewarm=False,
            ),
            injector=injector,
        )
        steps, next_req, alive = 0, 0, True
        while alive or next_req < n_requests:
            while next_req < n_requests and arrivals[next_req] <= steps:
                paged.submit(prompts[next_req])
                next_req += 1
            alive = paged.step()
            steps += 1
            assert steps < 5000, "chaos soak did not converge"
        _assert_clean_pool(paged)
        assert len(paged._finished) == n_requests
        return paged

    baseline = drive(None)
    base_out = {rid: r.out for rid, r in baseline._finished.items()}
    chaos = drive(FaultInjector(plan))
    repeat = drive(FaultInjector(plan)) if repeat_chaos else None
    return chaos, base_out, repeat


def _check_soak(chaos, base_out, plan):
    inj = chaos.injector
    for kind in ("device", "nan", "drafter", "alloc", "latency"):
        assert inj.counts[kind] >= 1, f"{kind} fault never fired"
    n_finished, n_failed = _assert_survivor_parity(chaos, base_out)
    assert n_failed >= 1          # device + nan faults kill their victims
    assert n_finished >= 1        # ...and never take the engine with them
    m = chaos.metrics
    assert m.faults_injected == inj.total_fired
    assert m.failed_requests == n_failed
    assert m.audit_violations == 0  # strict audits ran at every transition
    # prewarmed catalog held through the whole chaos run: nothing but
    # ladder-sanctioned gather twins compiled after the freeze
    assert m.prewarm_compiles > 0
    assert m.steadystate_compiles == 0
    # reproducibility: the same plan over the same workload fires the same
    # faults — (workload seed, FaultPlan) fully determines a chaos run
    return [f[:3] for f in inj.fired]


# tier-1 budget: each fault class has its own in-tier test; the
# all-classes chaos soak joins chaos_soak_long in the slow tier
@pytest.mark.slow
def test_chaos_soak_all_fault_classes(params):
    plan = FaultPlan(
        seed=7, drafter_rate=0.05, alloc_rate=0.02, latency_rate=0.05,
        latency_ms=0.1,
        schedule=(
            (5, "device"), (15, "nan"), (20, "drafter"),
            (25, "alloc"), (30, "latency"),
        ),
    )
    chaos, base_out, chaos2 = _chaos_soak(
        params, n_requests=12, arrival_span=50, max_new=10,
        plan=plan, workload_seed=1234, repeat_chaos=True,
    )
    fired = _check_soak(chaos, base_out, plan)
    assert [f[:3] for f in chaos2.injector.fired] == fired
    assert {r: q.out for r, q in chaos2._finished.items()} == {
        r: q.out for r, q in chaos._finished.items()
    }


def test_chaos_soak_script_smoke_mode():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))
    import chaos_soak

    record = chaos_soak.run_bench(chaos_soak.build_args(["--smoke"]))
    assert record.get("gate_failure") is None
    assert record["smoke"] is True
    assert record["failed"] >= 1 and record["finished"] >= 1
    assert all(n >= 1 for n in record["faults_by_kind"].values())
    assert record["audit_violations"] == 0


@pytest.mark.slow
def test_chaos_soak_long(params):
    plan = FaultPlan(
        seed=21, device_rate=0.004, nan_rate=0.004, drafter_rate=0.08,
        alloc_rate=0.03, latency_rate=0.08, latency_ms=0.1,
        schedule=(
            (10, "device"), (40, "nan"), (60, "drafter"),
            (80, "alloc"), (100, "latency"), (120, "device"), (140, "nan"),
        ),
    )
    chaos, base_out, _ = _chaos_soak(
        params, n_requests=30, arrival_span=160, max_new=14,
        plan=plan, workload_seed=4321,
    )
    _check_soak(chaos, base_out, plan)
