"""MoE inference tests: selective expert loading + Mixtral KV-cache decode.

Mirrors the reference's Mixtral inference model
(examples/inference/mixtral/neuron_modeling_mixtral.py) and the selective
expert-loading token-gen path (modules/moe/expert_mlps.py:267,298-357):
decode must route/compute identically to the training model so incremental
generation equals full recompute.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_llama3_2_tpu.inference import (
    InferenceEngine,
    GenerationConfig,
    LlamaDecode,
    MixtralDecode,
    SamplingConfig,
    decode_model_for,
)
from neuronx_distributed_llama3_2_tpu.models import (
    LLAMA_CONFIGS,
    MIXTRAL_CONFIGS,
    LlamaForCausalLM,
    MixtralForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.moe.experts import ExpertMLPs
from neuronx_distributed_llama3_2_tpu.moe.routing import top_k_routing

TINY_MOE = MIXTRAL_CONFIGS["tiny-moe"]


def _params():
    return MixtralForCausalLM(TINY_MOE).init(jax.random.key(0))


def test_selective_matches_all_experts():
    ex = ExpertMLPs(
        num_experts=8, hidden_size=16, intermediate_size=32, dtype=jnp.float32
    )
    params = ex.init(jax.random.key(1))
    t, k = 3, 2
    x = jax.random.normal(jax.random.key(2), (t, 16), jnp.float32)
    logits = jax.random.normal(jax.random.key(3), (t, 8), jnp.float32)
    gates, idx = top_k_routing(logits, k, normalize=True)
    y_sel = ex.forward_selective(params, x, gates, idx)
    y_all = ex.forward_all_experts(params, x, gates, idx)
    np.testing.assert_allclose(
        np.asarray(y_sel), np.asarray(y_all), atol=1e-5, rtol=1e-5
    )


def test_selective_dispatch_threshold(monkeypatch):
    """__call__ picks selective exactly when T·k <= E (the HBM-traffic
    crossover; role of the reference SELECTIVE_LOADING_THRESHOLD)."""
    ex = ExpertMLPs(
        num_experts=4, hidden_size=8, intermediate_size=16, dtype=jnp.float32
    )
    params = ex.init(jax.random.key(0))
    calls = []
    real_selective = ExpertMLPs.forward_selective
    monkeypatch.setattr(
        ExpertMLPs,
        "forward_selective",
        lambda self, *a, **k: (calls.append("sel"), real_selective(self, *a, **k))[1],
    )
    for t, expect_selective in ((1, True), (2, True), (5, False)):
        x = jax.random.normal(jax.random.key(t), (t, 8), jnp.float32)
        logits = jax.random.normal(jax.random.key(t + 10), (t, 4), jnp.float32)
        gates, idx = top_k_routing(logits, 2, normalize=True)
        calls.clear()
        y = ex(params, x, gates, idx)
        assert (len(calls) > 0) == expect_selective, (t, calls)
        y_ref = ex.forward_all_experts(params, x, gates, idx)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(y_ref), atol=1e-5, rtol=1e-5
        )


def test_decode_model_dispatch():
    assert isinstance(decode_model_for(TINY_MOE), MixtralDecode)
    llama = decode_model_for(LLAMA_CONFIGS["tiny"])
    assert isinstance(llama, LlamaDecode)
    assert not isinstance(llama, MixtralDecode)


def test_mixtral_incremental_decode_matches_recompute():
    """Prefill + per-token decode logits == full-model forward on the
    growing prefix (the MoE analogue of the Llama decode-parity gate)."""
    cfg = TINY_MOE
    model = MixtralForCausalLM(cfg)
    params = _params()
    decode = MixtralDecode(cfg)
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, cfg.vocab_size, size=(1, 8)).astype(np.int32)
    n_extra = 4

    cache = decode.init_cache(max_batch=1, max_len=32)
    ids = jnp.asarray(prompt)
    logits_pre, cache = decode.forward(
        params, cache, ids, jnp.zeros((1,), jnp.int32), context_encode=True
    )
    full = model(params, ids)
    np.testing.assert_allclose(
        np.asarray(logits_pre, np.float32), np.asarray(full, np.float32),
        atol=2e-4, rtol=2e-4,
    )

    seq = prompt[0].tolist()
    for step in range(n_extra):
        nxt = int(np.argmax(np.asarray(full)[0, -1]))
        seq.append(nxt)
        pos = jnp.asarray([len(seq) - 1], jnp.int32)
        logits_step, cache = decode.forward(
            params, cache, jnp.asarray([[nxt]], jnp.int32), pos
        )
        full = model(params, jnp.asarray([seq], jnp.int32))
        np.testing.assert_allclose(
            np.asarray(logits_step[:, 0], np.float32),
            np.asarray(full)[:, -1].astype(np.float32),
            atol=3e-4, rtol=3e-4,
        )


def test_mixtral_engine_greedy_generate():
    """End-to-end: the bucketed engine generates the same greedy tokens as
    an argmax loop over the training model's full forward."""
    cfg = dataclasses.replace(TINY_MOE, max_seq_len=128)
    model = MixtralForCausalLM(cfg)
    params = _params()
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, cfg.vocab_size, size=(6,)).tolist()
    n_new = 5

    engine = InferenceEngine(cfg, params, max_batch=1, max_seq_len=128)
    out = engine.generate(
        [prompt],
        GenerationConfig(
            max_new_tokens=n_new, sampling=SamplingConfig(greedy=True)
        ),
    )
    got = out.sequences[0]

    seq = list(prompt)
    want = []
    for _ in range(n_new):
        logits = model(params, jnp.asarray([seq], jnp.int32))
        nxt = int(np.argmax(np.asarray(logits)[0, -1]))
        want.append(nxt)
        seq.append(nxt)
    assert got == want


def test_mixtral_capacity_config_decode_never_drops():
    """A capacity-factor training config still decodes through the no-drop
    selective/all-experts paths (capacity dispatch is training-only)."""
    cfg = dataclasses.replace(TINY_MOE, capacity_factor=1.0)
    params = MixtralForCausalLM(cfg).init(jax.random.key(0))
    decode = MixtralDecode(cfg)
    cache = decode.init_cache(max_batch=2, max_len=16)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8)), jnp.int32
    )
    logits, cache = decode.forward(
        params, cache, ids, jnp.zeros((2,), jnp.int32), context_encode=True
    )
    # no-capacity config must agree: same weights, same routing, no dropping
    ref_logits, _ = MixtralDecode(TINY_MOE).forward(
        params, decode.init_cache(2, 16), ids, jnp.zeros((2,), jnp.int32),
        context_encode=True,
    )
    np.testing.assert_allclose(
        np.asarray(logits, np.float32), np.asarray(ref_logits, np.float32),
        atol=1e-5, rtol=1e-5,
    )
