"""Data pipeline + pretrain example tests (reference training_utils.py:99
loader/DistributedSampler semantics; resume determinism)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.data import (
    DistributedDataLoader,
    LoaderState,
    TokenDataset,
    write_token_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def token_file(tmp_path):
    path = str(tmp_path / "tokens.npy")
    write_token_file(path, np.arange(10_000, dtype=np.int32) % 256)
    return path


def test_token_dataset(token_file):
    ds = TokenDataset(token_file, seq_len=64)
    assert len(ds) == 10_000 // 64
    s0 = ds[0]
    assert s0.shape == (64,) and s0.dtype == np.int32
    np.testing.assert_array_equal(s0, np.arange(64) % 256)


def test_loader_deterministic_and_resumable(token_file):
    ds = TokenDataset(token_file, seq_len=64)
    a = DistributedDataLoader(ds, global_batch_size=4, seed=7)
    batches = [next(iter_a) for iter_a in [iter(a)] for _ in range(10)]

    # resume at step 6 reproduces batches 6..9 exactly
    b = DistributedDataLoader(
        ds, global_batch_size=4, seed=7, state=LoaderState(step=6)
    )
    for i, batch in zip(range(6, 10), iter(b)):
        np.testing.assert_array_equal(batch, batches[i])


def test_loader_epoch_reshuffle(token_file):
    ds = TokenDataset(token_file, seq_len=64)
    dl = DistributedDataLoader(ds, global_batch_size=4, seed=1)
    spe = dl.steps_per_epoch
    first_epoch0 = dl.batch_at(0)
    first_epoch1 = dl.batch_at(spe)
    assert not np.array_equal(first_epoch0, first_epoch1)


@pytest.mark.slow
def test_pretrain_script_resume(tmp_path):
    """Two invocations: train 4 steps + save, then resume and finish — the
    reference's latest_if_exists resume flow (run_llama_nxd.py:204-239),
    exercised end-to-end as a user would run it."""
    ckpt = str(tmp_path / "ckpt")
    cmd = [
        sys.executable, os.path.join(REPO, "examples", "pretrain_llama.py"),
        "--model", "tiny", "--cpu-devices", "4", "--tp", "2",
        "--global-batch", "4", "--seq-len", "32", "--synthetic", "20000",
        "--ckpt-dir", ckpt, "--save-every", "2",
        "--metrics-file", str(tmp_path / "m.jsonl"),
    ]
    env = dict(os.environ)
    r1 = subprocess.run(
        cmd + ["--steps", "4"], capture_output=True, text=True, env=env,
        timeout=480,
    )
    assert r1.returncode == 0, r1.stderr[-2000:]
    assert "done: 4 steps" in r1.stderr

    r2 = subprocess.run(
        cmd + ["--steps", "6"], capture_output=True, text=True, env=env,
        timeout=480,
    )
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "resumed from step_4 at step 4" in r2.stderr
    assert "done: 6 steps" in r2.stderr
    # metrics file recorded both runs
    lines = open(tmp_path / "m.jsonl").read().strip().splitlines()
    assert len(lines) == 6


def test_sample_range_holdout_disjoint(tmp_path):
    """Train/eval loaders over disjoint sample ranges never share a sample
    (the holdout split that replaces the reference's separate eval shard)."""
    import numpy as np

    from neuronx_distributed_llama3_2_tpu.data import (
        DistributedDataLoader,
        TokenDataset,
        write_token_file,
    )

    path = str(tmp_path / "t.npy")
    write_token_file(path, np.arange(64 * 40, dtype=np.int32))
    ds = TokenDataset(path, 64)  # 40 samples
    train = DistributedDataLoader(ds, 4, seed=0, sample_range=(0, 32))
    ev = DistributedDataLoader(ds, 4, shuffle=False, sample_range=(32, 40))

    seen_train = set()
    for step in range(16):  # 2 epochs
        for row in train.batch_at(step):
            seen_train.add(int(row[0]) // 64)
    seen_eval = set()
    for step in range(2):
        for row in ev.batch_at(step):
            seen_eval.add(int(row[0]) // 64)
    assert seen_train == set(range(32))
    assert seen_eval == set(range(32, 40))
    # fixed eval slice: the same batch every time
    np.testing.assert_array_equal(ev.batch_at(0), ev.batch_at(0))

    import pytest

    with pytest.raises(ValueError, match="sample_range"):
        DistributedDataLoader(ds, 4, sample_range=(30, 80))
    with pytest.raises(ValueError, match="samples < global batch"):
        DistributedDataLoader(ds, 16, sample_range=(32, 40))
