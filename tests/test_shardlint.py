"""shardlint: positive/negative fixtures per rule + the self-lint gate.

Each rule gets at least one snippet that MUST fire and one that MUST NOT
— the negative sides pin down the escape hatches the codebase relies on
(axis constants, ``__layout_deps__``, ``constrain``, suppression
comments). ``test_self_lint`` is the CI gate itself: the tree must stay
clean (or explicitly baselined) under its own analyzer.
"""

import os
import subprocess
import sys
import textwrap

from neuronx_distributed_llama3_2_tpu.analysis import (
    AxisEnv,
    RULES,
    lint_source,
    load_axis_env,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src, rule=None):
    findings = lint_source(textwrap.dedent(src), path="fixture.py")
    if rule is not None:
        findings = [f for f in findings if f.rule == rule]
    return findings


# ---------------------------------------------------------------- SL001


def test_sl001_literal_axis_fires():
    fs = _lint(
        """
        import jax

        def f(x):
            return jax.lax.psum(x, "tp")
        """,
        "SL001",
    )
    assert len(fs) == 1
    assert "'tp'" in fs[0].message
    assert "not a MESH_AXES member" not in fs[0].message


def test_sl001_unknown_axis_notes_typo():
    fs = _lint(
        """
        from jax import lax

        def f(x):
            return lax.all_gather(x, "tensor")
        """,
        "SL001",
    )
    assert len(fs) == 1
    assert "not a MESH_AXES member" in fs[0].message


def test_sl001_kwarg_and_wrapper_forms_fire():
    fs = _lint(
        """
        import jax
        from neuronx_distributed_llama3_2_tpu.parallel import mappings

        def f(x):
            a = jax.lax.ppermute(x, axis_name="dp", perm=[(0, 1)])
            b = mappings._all_gather(x, "cp")
            return a, b
        """,
        "SL001",
    )
    assert len(fs) == 2


def test_sl001_constant_or_parameter_ok():
    fs = _lint(
        """
        import jax
        from neuronx_distributed_llama3_2_tpu.parallel.state import TP_AXIS

        def f(x, axis):
            a = jax.lax.psum(x, TP_AXIS)
            b = jax.lax.psum(x, axis)
            return a, b
        """,
        "SL001",
    )
    assert fs == []


def test_sl001_suppression_comment():
    fs = _lint(
        """
        import jax

        def f(x):
            return jax.lax.psum(x, "tp")  # shardlint: disable=SL001
        """,
        "SL001",
    )
    assert fs == []


# ---------------------------------------------------------------- SL002


_SL002_POS = """
    import dataclasses
    from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state

    @dataclasses.dataclass(frozen=True)
    class Block:
        width: int

        def __call__(self, x):
            if parallel_state.sequence_parallel_enabled():
                return x * 2
            return x
"""


def test_sl002_undeclared_layout_reader_fires():
    fs = _lint(_SL002_POS, "SL002")
    assert len(fs) == 1
    assert "sequence_parallel_enabled" in fs[0].message
    assert "__layout_deps__" in fs[0].hint


def test_sl002_layout_deps_declaration_clears():
    fs = _lint(
        _SL002_POS.replace(
            "width: int",
            'width: int\n'
            '        __layout_deps__ = ("sequence_parallel_enabled",)',
        ),
        "SL002",
    )
    assert fs == []


def test_sl002_eq_false_dataclass_ok():
    # eq=False classes hash by identity — no stale-cache-key hazard
    fs = _lint(
        _SL002_POS.replace(
            "@dataclasses.dataclass(frozen=True)",
            "@dataclasses.dataclass(frozen=True, eq=False)",
        ),
        "SL002",
    )
    assert fs == []


def test_sl002_plain_class_ok():
    fs = _lint(
        """
        from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state

        class Block:
            def __call__(self, x):
                return x * parallel_state.get_tensor_model_parallel_size()
        """,
        "SL002",
    )
    assert fs == []


# ---------------------------------------------------------------- SL003


def test_sl003_spec_arity_exceeds_rank_fires():
    fs = _lint(
        """
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from neuronx_distributed_llama3_2_tpu.parallel.layers import constrain

        def f():
            x = jnp.zeros((4, 8))
            return constrain(x, P(None, "tp", None))
        """,
        "SL003",
    )
    assert len(fs) == 1
    assert "3 entries" in fs[0].message and "rank 2" in fs[0].message


def test_sl003_matching_or_shorter_spec_ok():
    fs = _lint(
        """
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def f(y):
            x = jnp.zeros((4, 8, 2))
            a = lax.with_sharding_constraint(x, P(None, "tp"))
            b = lax.with_sharding_constraint(y, P(None, None, None, None))
            x = y  # reassignment: rank no longer known
            c = lax.with_sharding_constraint(x, P(None, "tp", None, None))
            return a, b, c
        """,
        "SL003",
    )
    assert fs == []


def test_sl003_reshape_rank_inference():
    fs = _lint(
        """
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def f(y):
            x = y.reshape(4, 8)
            return lax.with_sharding_constraint(x, P("dp", None, "tp"))
        """,
        "SL003",
    )
    assert len(fs) == 1


# ---------------------------------------------------------------- SL004


def test_sl004_host_effects_in_jit_fire():
    fs = _lint(
        """
        import time
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            t = time.time()
            y = np.asarray(x)
            print(t)
            x.block_until_ready()
            return x
        """,
        "SL004",
    )
    assert len(fs) == 4
    assert any(".block_until_ready()" in f.message for f in fs)


def test_sl004_traced_callee_of_scan_and_shard_map():
    fs = _lint(
        """
        import jax
        from jax import lax
        from neuronx_distributed_llama3_2_tpu.utils import compat

        def body(c, x):
            print(x)
            return c, x

        def g(x):
            import time
            time.time()
            return x

        def run(mesh, xs):
            lax.scan(body, 0, xs)
            compat.shard_map(g, mesh, in_specs=None, out_specs=None)(xs)
        """,
        "SL004",
    )
    assert len(fs) == 2


def test_sl004_host_calls_outside_traces_ok():
    fs = _lint(
        """
        import time

        def setup(x):
            t = time.time()
            print(t)
            return x
        """,
        "SL004",
    )
    assert fs == []


# ---------------------------------------------------------------- SL005


def test_sl005_raw_constraint_in_shard_map_fires():
    fs = _lint(
        """
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P
        from neuronx_distributed_llama3_2_tpu.utils import compat

        def body(x):
            return lax.with_sharding_constraint(x, P("tp"))

        def run(mesh, x):
            return compat.shard_map(
                body, mesh, in_specs=P("tp"), out_specs=P("tp")
            )(x)
        """,
        "SL005",
    )
    assert len(fs) == 1
    assert "constrain" in fs[0].hint


def test_sl005_blessed_constrain_ok():
    fs = _lint(
        """
        from jax.sharding import PartitionSpec as P
        from neuronx_distributed_llama3_2_tpu.parallel.layers import constrain
        from neuronx_distributed_llama3_2_tpu.utils import compat

        def body(x):
            return constrain(x, P("tp"))

        def run(mesh, x):
            return compat.shard_map(
                body, mesh, in_specs=P("tp"), out_specs=P("tp")
            )(x)
        """,
        "SL005",
    )
    assert fs == []


def test_sl005_constraint_outside_shard_map_ok():
    fs = _lint(
        """
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def f(x):
            return lax.with_sharding_constraint(x, P("tp"))
        """,
        "SL005",
    )
    assert fs == []


# ---------------------------------------------------------------- SL006


def test_sl006_unbound_axis_fires():
    fs = _lint(
        """
        from jax import lax
        from neuronx_distributed_llama3_2_tpu.utils import compat

        def body(x):
            return x + lax.axis_index("dp")

        def run(mesh, x):
            return compat.shard_map(
                body, mesh, in_specs=None, out_specs=None,
                axis_names={"tp"},
            )(x)
        """,
        "SL006",
    )
    assert len(fs) == 1
    assert "'dp'" in fs[0].message and "['tp']" in fs[0].message


def test_sl006_bound_axis_and_unknown_axis_names_ok():
    fs = _lint(
        """
        from jax import lax
        from neuronx_distributed_llama3_2_tpu.utils import compat

        def body(x):
            return x + lax.axis_index("tp")

        def dyn(x):
            return x + lax.axis_index("dp")

        def run(mesh, x, names):
            a = compat.shard_map(
                body, mesh, in_specs=None, out_specs=None,
                axis_names={"tp"},
            )(x)
            # axis_names not statically resolvable: rule must stay quiet
            b = compat.shard_map(
                dyn, mesh, in_specs=None, out_specs=None, axis_names=names
            )(x)
            return a, b
        """,
        "SL006",
    )
    assert fs == []


# ---------------------------------------------------------------- SL007


def test_sl007_adhoc_donated_jit_in_serving_fires():
    src = """
    import jax

    class Engine:
        def __init__(self):
            self._fn = jax.jit(lambda c: c, donate_argnums=(0,))
    """
    fs = lint_source(
        textwrap.dedent(src),
        path="neuronx_distributed_llama3_2_tpu/serving/engine.py",
    )
    fs = [f for f in fs if f.rule == "SL007"]
    assert len(fs) == 1
    assert "_register_program" in fs[0].message + fs[0].hint


def test_sl007_registry_helper_and_other_layers_quiet():
    src = """
    import jax

    class Engine:
        def _register_program(self, key_, fn, donate_argnums=()):
            rec = jax.jit(fn, donate_argnums=donate_argnums)
            self._programs[key_] = rec
            return rec

        def _plain(self, fn):
            return jax.jit(fn)  # undonated: not a registry concern
    """
    fs = lint_source(
        textwrap.dedent(src),
        path="neuronx_distributed_llama3_2_tpu/serving/engine.py",
    )
    assert [f for f in fs if f.rule == "SL007"] == []
    # same donated jit OUTSIDE serving/: a different layer's business
    outside = """
    import jax

    many = jax.jit(lambda c: c, donate_argnums=(0,))
    """
    fs = lint_source(
        textwrap.dedent(outside),
        path="neuronx_distributed_llama3_2_tpu/inference/runner.py",
    )
    assert [f for f in fs if f.rule == "SL007"] == []


def test_sl007_donate_argnames_spelling_fires():
    src = """
    from jax import jit

    step = jit(lambda c: c, donate_argnames=("cache",))
    """
    fs = lint_source(
        textwrap.dedent(src),
        path="neuronx_distributed_llama3_2_tpu/serving/scheduler.py",
    )
    assert [f.rule for f in fs if f.rule == "SL007"] == ["SL007"]


# ---------------------------------------------------------------- SL008


_SERVING = "neuronx_distributed_llama3_2_tpu/serving/engine.py"


def test_sl008_mirror_write_outside_funnel_fires():
    src = """
    class Engine:
        def _my_new_path(self, lane):
            self._positions[lane] += 1  # poking the frontier mirror
    """
    fs = lint_source(textwrap.dedent(src), path=_SERVING)
    fs = [f for f in fs if f.rule == "SL008"]
    assert len(fs) == 1
    assert "_positions" in fs[0].message


def test_sl008_resident_and_tuple_targets_fire():
    src = """
    class Engine:
        def refresh(self, x):
            self._d_tokens = x            # resident outside a funnel

        def unpack(self, a, b):
            self._tokens, other = a, b    # tuple-target mirror write
    """
    fs = [f for f in lint_source(textwrap.dedent(src), path=_SERVING)
          if f.rule == "SL008"]
    assert len(fs) == 2


def test_sl008_blessed_funnels_and_other_layers_quiet():
    src = """
    class Engine:
        def _read_and_apply(self, lane):
            self._positions[lane] -= 1    # mirror funnel

        def _flush_state(self, x):
            self._d_tokens = x            # resident funnel

        def _my_new_path(self):
            self._scratch = 0             # unprotected attr: fine
    """
    fs = lint_source(textwrap.dedent(src), path=_SERVING)
    assert [f for f in fs if f.rule == "SL008"] == []
    # the same rogue write OUTSIDE serving/ is not SL008's business
    outside = """
    class Thing:
        def poke(self, lane):
            self._positions[lane] = 0
    """
    fs = lint_source(
        textwrap.dedent(outside),
        path="neuronx_distributed_llama3_2_tpu/inference/runner.py",
    )
    assert [f for f in fs if f.rule == "SL008"] == []


def test_sl008_line_suppression():
    src = """
    class Engine:
        def _my_new_path(self, lane):
            self._positions[lane] = 0  # shardlint: disable=SL008
    """
    fs = lint_source(textwrap.dedent(src), path=_SERVING)
    assert [f for f in fs if f.rule == "SL008"] == []


# ----------------------------------------------------------- machinery


def test_fingerprint_survives_line_moves():
    src = """
    import jax

    def f(x):
        return jax.lax.psum(x, "tp")
    """
    a = _lint(src, "SL001")[0]
    b = _lint("\n\n# a comment\n" + textwrap.dedent(src), "SL001")[0]
    assert a.line != b.line
    assert a.fingerprint == b.fingerprint


def test_skip_file_comment():
    fs = _lint(
        """
        # shardlint: skip-file
        import jax

        def f(x):
            return jax.lax.psum(x, "tp")
        """
    )
    assert fs == []


def test_load_axis_env_matches_state_py():
    env = load_axis_env(REPO_ROOT)
    assert env.axes == frozenset({"pp", "dp", "cp", "ep", "tp"})
    assert env.constants["TP_AXIS"] == "tp"
    assert AxisEnv.default().axes == env.axes


def test_rule_catalogue_complete():
    assert sorted(RULES) == [
        "SL001", "SL002", "SL003", "SL004", "SL005", "SL006", "SL007",
        "SL008",
    ]


# ------------------------------------------------------------ the gate


def test_self_lint():
    """The tier-1 CI gate: the repo's own sources must be shardlint-clean
    (modulo the reviewed baseline). Runs the real CLI so the exit-status
    contract is what's tested."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "shardlint_gate.py"), "--self"],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, (
        "shardlint gate failed:\n" + proc.stdout + proc.stderr
    )
