"""Paged serving engine e2e: greedy equivalence vs the dense slot scheduler,
prefix-cache admission, copy-on-write, and graceful pool exhaustion.

The equivalence property is the whole gate: the paged block-table path must
produce token-identical greedy outputs to the dense seq_ids-scatter path on
fp32 CPU (the same exactness the incremental-vs-recompute and bucket-ladder
tests already establish for the dense programs)."""

import dataclasses

import jax
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.inference import (
    ContinuousBatchingEngine,
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.analysis.graftcheck import (
    all_shapes,
    audit_programs,
)
from neuronx_distributed_llama3_2_tpu.serving import (
    PagedConfig,
    PagedServingEngine,
    audit_engine,
    make_serving_engine,
)

TINY = LLAMA_CONFIGS["tiny"]


@pytest.fixture(scope="module")
def params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


def _engine(params, max_batch=4, max_seq_len=64, buckets=(8, 16, 32)):
    return InferenceEngine(
        TINY, params,
        max_batch=max_batch, max_seq_len=max_seq_len, buckets=list(buckets),
    )


def _dense_outputs(params, prompts, gen, **kw):
    dense = ContinuousBatchingEngine(_engine(params, **kw), gen)
    for p in prompts:
        dense.submit(p)
    return dense.run_to_completion()


def _prompts(rng, lengths):
    return [
        rng.integers(0, TINY.vocab_size, size=(n,)).tolist() for n in lengths
    ]


def test_paged_matches_dense_on_mixed_length_batch(params):
    gen = GenerationConfig(max_new_tokens=8)
    prompts = _prompts(np.random.default_rng(3), (5, 12, 20, 9, 17, 3))
    paged = PagedServingEngine(
        _engine(params), gen, PagedConfig(block_size=8, num_blocks=64)
    )
    for p in prompts:
        paged.submit(p)
    out = paged.run_to_completion()
    assert out == _dense_outputs(params, prompts, gen)
    m = paged.metrics
    assert m.finished == len(prompts)
    assert paged.allocator.active_blocks == 0  # everything released
    assert paged.allocator.leak_check() == []
    assert audit_engine(paged) == []
    assert audit_programs(paged) == []


def test_prefix_reuse_reports_cached_tokens_and_stays_equivalent(params):
    gen = GenerationConfig(max_new_tokens=6)
    rng = np.random.default_rng(5)
    shared = rng.integers(0, TINY.vocab_size, size=(24,)).tolist()
    prompts = [
        shared + rng.integers(0, TINY.vocab_size, size=(4,)).tolist()
        for _ in range(6)
    ]
    paged = PagedServingEngine(
        _engine(params, max_batch=2), gen,
        PagedConfig(block_size=8, num_blocks=64),
    )
    for p in prompts:
        paged.submit(p)
    out = paged.run_to_completion()
    assert out == _dense_outputs(params, prompts, gen, max_batch=2)
    # first request prefills everything; later ones admit the shared 24
    # tokens (3 full blocks) by reference
    infos = [paged.request_info(r) for r in range(len(prompts))]
    assert infos[0]["cached_tokens"] == 0
    assert all(i["cached_tokens"] == 24 for i in infos[1:])
    m = paged.metrics
    assert m.cached_tokens == 24 * 5
    assert m.prefix_skip_fraction() > 0.5
    assert paged.index.hit_rate() > 0.5


def test_pool_exhaustion_preempts_requeues_and_completes(params):
    # 9 usable blocks, 4 requests that each grow to 6 blocks: decode MUST
    # exhaust the pool; the engine preempts the youngest and requeues —
    # run_to_completion finishes everyone with no exception and the final
    # tokens are identical to the uncontended dense run (greedy recompute
    # determinism)
    gen = GenerationConfig(max_new_tokens=36)
    prompts = _prompts(np.random.default_rng(5), (12, 12, 12, 12))
    for caching in (False, True):
        paged = PagedServingEngine(
            _engine(params), gen,
            PagedConfig(
                block_size=8, num_blocks=10, decode_reserve_blocks=1,
                enable_prefix_caching=caching,
            ),
        )
        for p in prompts:
            paged.submit(p)
        out = paged.run_to_completion()
        assert out == _dense_outputs(params, prompts, gen)
        assert paged.metrics.preemptions > 0
        assert paged.metrics.finished == 4
        if caching:
            assert paged.allocator.evictions > 0


def test_copy_on_write_on_partial_block_share(params):
    # phase 1 finishes a request whose final partial block gets registered;
    # phase 2's prompt diverges INSIDE that block -> token-granular match +
    # copy-on-write before the suffix write
    gen = GenerationConfig(max_new_tokens=4)
    rng = np.random.default_rng(11)
    base = rng.integers(0, TINY.vocab_size, size=(27,)).tolist()
    p1 = base + [1]
    p2 = base + [2, 3]  # diverges at token 27, mid-block for block_size=8
    paged = PagedServingEngine(
        _engine(params), gen, PagedConfig(block_size=8, num_blocks=64)
    )
    paged.submit(p1)
    out1 = paged.run_to_completion()
    paged.submit(p2)
    out2 = paged.run_to_completion()
    assert paged.allocator.cow_copies >= 1
    assert paged.request_info(1)["cached_tokens"] == 27
    dense = _dense_outputs(params, [p1, p2], gen)
    assert {0: out1[0], 1: out2[1]} == dense


@pytest.mark.slow  # tier-1 time budget; prefix reuse covered by the cached-tokens test
def test_acceptance_prefix_workload():
    # the ISSUE acceptance bar, via the bench entry point: 16 requests
    # sharing a 256-token prefix -> >=50% of prefill tokens skipped AND
    # token-identical to the dense engine
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))
    import kv_block_bench

    record = kv_block_bench.run_bench(kv_block_bench.build_args([]))
    assert record.get("gate_failure") is None
    assert record["dense_equivalent"] is True
    assert record["prefix_skip_fraction"] >= 0.5
    assert record["cached_tokens"] >= 15 * 256


def test_bench_smoke_mode():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))
    import kv_block_bench

    record = kv_block_bench.run_bench(kv_block_bench.build_args(["--smoke"]))
    assert record.get("gate_failure") is None
    assert record["smoke"] is True
    assert record["dense_equivalent"] is True


def test_submit_validation(params):
    gen = GenerationConfig(max_new_tokens=8)
    paged = PagedServingEngine(
        _engine(params), gen,
        PagedConfig(block_size=8, num_blocks=6), precompile=False,
    )
    with pytest.raises(ValueError, match="cache capacity"):
        paged.submit(list(range(60)))  # 60 + 8 > max_seq_len 64
    with pytest.raises(ValueError, match="blocks"):
        paged.submit(list(range(30)))  # needs 5+reserve > 5 usable
    with pytest.raises(ValueError, match="decode_reserve_blocks"):
        PagedServingEngine(
            _engine(params), gen,
            PagedConfig(block_size=8, decode_reserve_blocks=0),
            precompile=False,
        )


def test_make_serving_engine_flag(params):
    gen = GenerationConfig(max_new_tokens=4)
    assert isinstance(
        make_serving_engine(_engine(params), gen, paged=None, precompile=False),
        ContinuousBatchingEngine,
    )
    assert isinstance(
        make_serving_engine(
            _engine(params), gen,
            paged=PagedConfig(block_size=8, num_blocks=32), precompile=False,
        ),
        PagedServingEngine,
    )


def test_metrics_snapshot_shape(params):
    gen = GenerationConfig(max_new_tokens=4)
    paged = PagedServingEngine(
        _engine(params), gen, PagedConfig(block_size=8, num_blocks=32)
    )
    paged.submit(_prompts(np.random.default_rng(0), (10,))[0])
    paged.run_to_completion()
    snap = paged.metrics.snapshot(paged.allocator, paged.index)
    for key in (
        "submitted", "finished", "preemptions", "prefill_tokens",
        "cached_tokens", "prefix_skip_fraction", "block_utilization",
        "free_blocks", "prefix_hit_rate", "radix_nodes",
        "decode_steps_async", "lame_duck_tokens", "sync_fallbacks",
        "lane_syncs", "table_deltas", "h2d_uploads",
        "host_schedule_ms_per_step", "device_wait_ms_per_step",
    ):
        assert key in snap


# ---------------------------------------------------------------------------
# gather-free decode kernel (use_paged_kernel) + chunked prefill
# ---------------------------------------------------------------------------

TINY_KERNEL = dataclasses.replace(TINY, use_paged_kernel=True)


def _kernel_engine(params, max_batch=4, max_seq_len=64, buckets=(8, 16, 32)):
    return InferenceEngine(
        TINY_KERNEL, params,
        max_batch=max_batch, max_seq_len=max_seq_len, buckets=list(buckets),
    )


def test_paged_kernel_engine_matches_dense(params):
    """Acceptance: with use_paged_kernel, run_to_completion greedy outputs
    are token-identical to the dense engine on the mixed-length fixture."""
    gen = GenerationConfig(max_new_tokens=8)
    prompts = _prompts(np.random.default_rng(3), (5, 12, 20, 9, 17, 3))
    paged = PagedServingEngine(
        _kernel_engine(params), gen, PagedConfig(block_size=8, num_blocks=64)
    )
    for p in prompts:
        paged.submit(p)
    assert paged.run_to_completion() == _dense_outputs(params, prompts, gen)


def test_paged_kernel_cow_partial_prefix_matches_dense(params):
    """Kernel path over a partially-shared prefix: the second prompt
    diverges mid-block, so its table carries a COW copy — outputs must
    still match dense exactly."""
    gen = GenerationConfig(max_new_tokens=4)
    rng = np.random.default_rng(11)
    base = rng.integers(0, TINY.vocab_size, size=(27,)).tolist()
    p1 = base + [1]
    p2 = base + [2, 3]  # diverges at token 27, mid-block for block_size=8
    paged = PagedServingEngine(
        _kernel_engine(params), gen, PagedConfig(block_size=8, num_blocks=64)
    )
    paged.submit(p1)
    out1 = paged.run_to_completion()
    paged.submit(p2)
    out2 = paged.run_to_completion()
    assert paged.allocator.cow_copies >= 1
    assert paged.request_info(1)["cached_tokens"] == 27
    dense = _dense_outputs(params, [p1, p2], gen)
    assert {0: out1[0], 1: out2[1]} == dense


def test_paged_kernel_chunked_prefill_matches_dense(params):
    """Kernel + chunked prefill together (the full tentpole config)."""
    gen = GenerationConfig(max_new_tokens=8)
    prompts = _prompts(np.random.default_rng(3), (5, 30, 20, 9, 26, 3))
    paged = PagedServingEngine(
        _kernel_engine(params), gen,
        PagedConfig(block_size=8, num_blocks=64, prefill_chunk_tokens=8),
    )
    for p in prompts:
        paged.submit(p)
    out = paged.run_to_completion()
    assert out == _dense_outputs(params, prompts, gen)
    assert paged.metrics.prefill_chunks > 0


def test_paged_kernel_decode_never_materializes_gather(params):
    """Acceptance: the decode jaxpr must not contain a (b, kv_limit, NKV, D)
    gathered K/V array anywhere (including nested scan/jit sub-jaxprs) when
    the kernel is on — and must contain it when it is off (sanity check
    that the assertion actually detects the gather)."""
    import jax.numpy as jnp

    from neuronx_distributed_llama3_2_tpu.inference.model import LlamaDecode

    b, kv_limit, nb, bs, w = 4, 32, 16, 8, 8
    forbidden = (b, kv_limit, TINY.num_kv_heads, TINY.head_dim)
    for flag, expect_gather in ((False, True), (True, False)):
        cfg = dataclasses.replace(TINY, use_paged_kernel=flag)
        model = LlamaDecode(cfg)
        cache = model.init_paged_cache(nb, bs)
        closed = jax.make_jaxpr(
            lambda p, c, t, ps, tb: model.forward(  # noqa: B023
                p, c, t, ps, None, block_tables=tb, kv_limit=kv_limit
            )
        )(
            params, cache, jnp.zeros((b, 1), jnp.int32),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b, w), jnp.int32),
        )
        shapes = all_shapes(closed)
        assert (forbidden in shapes) is expect_gather, (
            f"use_paged_kernel={flag}: gather aval {forbidden} "
            f"{'missing' if expect_gather else 'present'} in decode jaxpr"
        )


def test_chunked_prefill_interleaves_decode(params):
    """Acceptance: with prefill_chunk_tokens set, a long-prompt admission
    interleaves — the already-active lane gains a decode token on the same
    steps that advance the new request's prefill chunks."""
    gen = GenerationConfig(max_new_tokens=16)
    rng = np.random.default_rng(9)
    pa = rng.integers(0, TINY.vocab_size, size=(4,)).tolist()
    pb = rng.integers(0, TINY.vocab_size, size=(32,)).tolist()
    paged = PagedServingEngine(
        _engine(params), gen,
        PagedConfig(block_size=8, num_blocks=64, prefill_chunk_tokens=8),
    )
    ra = paged.submit(pa)
    paged.step()  # A admitted (short prompt: unchunked) and decoding
    rb = paged.submit(pb)
    trace = []  # (A generated, B prefill progress, B still prefilling)
    for _ in range(4):
        paged.step()
        a, b = paged._requests[ra], paged._requests[rb]
        trace.append((len(a.out), b.prefill_pos, b.prefilling))
    # B took all 4 steps of chunked prefill (32 tokens / 8 per chunk) ...
    assert [t[1] for t in trace] == [8, 16, 24, 32]
    assert [t[2] for t in trace] == [True, True, True, False]
    # ... and A decoded one token on every one of those steps
    assert [t[0] for t in trace] == [3, 4, 5, 6]
    assert paged.metrics.prefill_chunks == 4
    assert paged.request_info(rb)["prefilling"] is False
    out = paged.run_to_completion()
    assert out == _dense_outputs(params, [pa, pb], gen)


def test_preempt_resume_mid_chunked_prefill(params):
    """An older lane's decode growth exhausts the pool while a younger
    request is mid-chunked-prefill: the victim is caught prefilling, is
    requeued, re-admits, and the final outputs still match dense."""
    gen = GenerationConfig(max_new_tokens=8)
    rng = np.random.default_rng(21)
    pa = rng.integers(0, TINY.vocab_size, size=(8,)).tolist()
    pb = rng.integers(0, TINY.vocab_size, size=(30,)).tolist()
    paged = PagedServingEngine(
        _engine(params), gen,
        PagedConfig(
            block_size=4, num_blocks=12, decode_reserve_blocks=1,
            prefill_chunk_tokens=4,
        ),
    )
    preempted = []  # (rid, was_prefilling) at preemption time
    orig = paged._preempt

    def spy(req):
        preempted.append((req.rid, req.prefilling))
        orig(req)

    paged._preempt = spy
    ra = paged.submit(pa)
    rb = paged.submit(pb)
    out = paged.run_to_completion()
    assert (rb, True) in preempted, preempted
    assert paged.request_info(rb)["preemptions"] >= 1
    assert out == _dense_outputs(params, [pa, pb], gen)
    assert paged.allocator.active_blocks == 0
    del ra


def test_request_info_map_covers_all_lifecycle_states(params):
    gen = GenerationConfig(max_new_tokens=4)
    paged = PagedServingEngine(
        _engine(params, max_batch=1), gen,
        PagedConfig(block_size=8, num_blocks=64),
    )
    r0 = paged.submit(_prompts(np.random.default_rng(0), (10,))[0])
    r1 = paged.submit(_prompts(np.random.default_rng(1), (10,))[0])
    paged.step()  # r0 active (sole lane), r1 still queued
    assert paged.request_info(r0)["generated_tokens"] >= 1
    assert paged.request_info(r1)["generated_tokens"] == 0
    paged.run_to_completion()
    assert paged.request_info(r0)["done"] is True
    assert paged.request_info(r1)["done"] is True
    with pytest.raises(KeyError, match="unknown request id"):
        paged.request_info(99)


def test_admit_blocked_counter(params):
    """Admission deferrals on the block budget are counted (and flow into
    the metrics log line via snapshot())."""
    gen = GenerationConfig(max_new_tokens=8)
    prompts = _prompts(np.random.default_rng(5), (12, 12, 12, 12))
    paged = PagedServingEngine(
        _engine(params), gen,
        PagedConfig(block_size=8, num_blocks=10, decode_reserve_blocks=1),
    )
    for p in prompts:
        paged.submit(p)
    paged.run_to_completion()
    assert paged.metrics.admit_blocked > 0
    snap = paged.metrics.snapshot(paged.allocator, paged.index)
    assert "admit_blocked" in snap and "prefill_chunks" in snap
