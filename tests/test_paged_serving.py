"""Paged serving engine e2e: greedy equivalence vs the dense slot scheduler,
prefix-cache admission, copy-on-write, and graceful pool exhaustion.

The equivalence property is the whole gate: the paged block-table path must
produce token-identical greedy outputs to the dense seq_ids-scatter path on
fp32 CPU (the same exactness the incremental-vs-recompute and bucket-ladder
tests already establish for the dense programs)."""

import dataclasses

import jax
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.inference import (
    ContinuousBatchingEngine,
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.serving import (
    PagedConfig,
    PagedServingEngine,
    make_serving_engine,
)

TINY = LLAMA_CONFIGS["tiny"]


@pytest.fixture(scope="module")
def params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


def _engine(params, max_batch=4, max_seq_len=64, buckets=(8, 16, 32)):
    return InferenceEngine(
        TINY, params,
        max_batch=max_batch, max_seq_len=max_seq_len, buckets=list(buckets),
    )


def _dense_outputs(params, prompts, gen, **kw):
    dense = ContinuousBatchingEngine(_engine(params, **kw), gen)
    for p in prompts:
        dense.submit(p)
    return dense.run_to_completion()


def _prompts(rng, lengths):
    return [
        rng.integers(0, TINY.vocab_size, size=(n,)).tolist() for n in lengths
    ]


def test_paged_matches_dense_on_mixed_length_batch(params):
    gen = GenerationConfig(max_new_tokens=8)
    prompts = _prompts(np.random.default_rng(3), (5, 12, 20, 9, 17, 3))
    paged = PagedServingEngine(
        _engine(params), gen, PagedConfig(block_size=8, num_blocks=64)
    )
    for p in prompts:
        paged.submit(p)
    out = paged.run_to_completion()
    assert out == _dense_outputs(params, prompts, gen)
    m = paged.metrics
    assert m.finished == len(prompts)
    assert paged.allocator.active_blocks == 0  # everything released


def test_prefix_reuse_reports_cached_tokens_and_stays_equivalent(params):
    gen = GenerationConfig(max_new_tokens=6)
    rng = np.random.default_rng(5)
    shared = rng.integers(0, TINY.vocab_size, size=(24,)).tolist()
    prompts = [
        shared + rng.integers(0, TINY.vocab_size, size=(4,)).tolist()
        for _ in range(6)
    ]
    paged = PagedServingEngine(
        _engine(params, max_batch=2), gen,
        PagedConfig(block_size=8, num_blocks=64),
    )
    for p in prompts:
        paged.submit(p)
    out = paged.run_to_completion()
    assert out == _dense_outputs(params, prompts, gen, max_batch=2)
    # first request prefills everything; later ones admit the shared 24
    # tokens (3 full blocks) by reference
    infos = [paged.request_info(r) for r in range(len(prompts))]
    assert infos[0]["cached_tokens"] == 0
    assert all(i["cached_tokens"] == 24 for i in infos[1:])
    m = paged.metrics
    assert m.cached_tokens == 24 * 5
    assert m.prefix_skip_fraction() > 0.5
    assert paged.index.hit_rate() > 0.5


def test_pool_exhaustion_preempts_requeues_and_completes(params):
    # 9 usable blocks, 4 requests that each grow to 6 blocks: decode MUST
    # exhaust the pool; the engine preempts the youngest and requeues —
    # run_to_completion finishes everyone with no exception and the final
    # tokens are identical to the uncontended dense run (greedy recompute
    # determinism)
    gen = GenerationConfig(max_new_tokens=36)
    prompts = _prompts(np.random.default_rng(5), (12, 12, 12, 12))
    for caching in (False, True):
        paged = PagedServingEngine(
            _engine(params), gen,
            PagedConfig(
                block_size=8, num_blocks=10, decode_reserve_blocks=1,
                enable_prefix_caching=caching,
            ),
        )
        for p in prompts:
            paged.submit(p)
        out = paged.run_to_completion()
        assert out == _dense_outputs(params, prompts, gen)
        assert paged.metrics.preemptions > 0
        assert paged.metrics.finished == 4
        if caching:
            assert paged.allocator.evictions > 0


def test_copy_on_write_on_partial_block_share(params):
    # phase 1 finishes a request whose final partial block gets registered;
    # phase 2's prompt diverges INSIDE that block -> token-granular match +
    # copy-on-write before the suffix write
    gen = GenerationConfig(max_new_tokens=4)
    rng = np.random.default_rng(11)
    base = rng.integers(0, TINY.vocab_size, size=(27,)).tolist()
    p1 = base + [1]
    p2 = base + [2, 3]  # diverges at token 27, mid-block for block_size=8
    paged = PagedServingEngine(
        _engine(params), gen, PagedConfig(block_size=8, num_blocks=64)
    )
    paged.submit(p1)
    out1 = paged.run_to_completion()
    paged.submit(p2)
    out2 = paged.run_to_completion()
    assert paged.allocator.cow_copies >= 1
    assert paged.request_info(1)["cached_tokens"] == 27
    dense = _dense_outputs(params, [p1, p2], gen)
    assert {0: out1[0], 1: out2[1]} == dense


def test_acceptance_prefix_workload():
    # the ISSUE acceptance bar, via the bench entry point: 16 requests
    # sharing a 256-token prefix -> >=50% of prefill tokens skipped AND
    # token-identical to the dense engine
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))
    import kv_block_bench

    record = kv_block_bench.run_bench(kv_block_bench.build_args([]))
    assert record.get("gate_failure") is None
    assert record["dense_equivalent"] is True
    assert record["prefix_skip_fraction"] >= 0.5
    assert record["cached_tokens"] >= 15 * 256


def test_bench_smoke_mode():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))
    import kv_block_bench

    record = kv_block_bench.run_bench(kv_block_bench.build_args(["--smoke"]))
    assert record.get("gate_failure") is None
    assert record["smoke"] is True
    assert record["dense_equivalent"] is True


def test_submit_validation(params):
    gen = GenerationConfig(max_new_tokens=8)
    paged = PagedServingEngine(
        _engine(params), gen,
        PagedConfig(block_size=8, num_blocks=6), precompile=False,
    )
    with pytest.raises(ValueError, match="cache capacity"):
        paged.submit(list(range(60)))  # 60 + 8 > max_seq_len 64
    with pytest.raises(ValueError, match="blocks"):
        paged.submit(list(range(30)))  # needs 5+reserve > 5 usable
    with pytest.raises(ValueError, match="decode_reserve_blocks"):
        PagedServingEngine(
            _engine(params), gen,
            PagedConfig(block_size=8, decode_reserve_blocks=0),
            precompile=False,
        )


def test_make_serving_engine_flag(params):
    gen = GenerationConfig(max_new_tokens=4)
    assert isinstance(
        make_serving_engine(_engine(params), gen, paged=None, precompile=False),
        ContinuousBatchingEngine,
    )
    assert isinstance(
        make_serving_engine(
            _engine(params), gen,
            paged=PagedConfig(block_size=8, num_blocks=32), precompile=False,
        ),
        PagedServingEngine,
    )


def test_metrics_snapshot_shape(params):
    gen = GenerationConfig(max_new_tokens=4)
    paged = PagedServingEngine(
        _engine(params), gen, PagedConfig(block_size=8, num_blocks=32)
    )
    paged.submit(_prompts(np.random.default_rng(0), (10,))[0])
    paged.run_to_completion()
    snap = paged.metrics.snapshot(paged.allocator, paged.index)
    for key in (
        "submitted", "finished", "preemptions", "prefill_tokens",
        "cached_tokens", "prefix_skip_fraction", "block_utilization",
        "free_blocks", "prefix_hit_rate", "radix_nodes",
    ):
        assert key in snap
