"""Native (C++) token loader tests: builds the shared lib with g++ and
checks byte-exact parity with the numpy path, prefetch, and dtype widths."""

import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.data.dataset import (
    TokenDataset,
    write_token_file,
)
from neuronx_distributed_llama3_2_tpu.data import native_loader

pytestmark = pytest.mark.skipif(
    not native_loader.native_available(),
    reason="no C++ toolchain / native lib",
)


@pytest.fixture(scope="module")
def token_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("tok") / "tokens.npy")
    rng = np.random.default_rng(0)
    write_token_file(path, rng.integers(0, 50000, 4096).astype(np.int32))
    return path


def test_matches_numpy_dataset(token_file):
    py = TokenDataset(token_file, seq_len=64)
    nat = native_loader.NativeTokenDataset(token_file, seq_len=64)
    assert len(py) == len(nat) == 64
    for i in [0, 1, 17, 63]:
        np.testing.assert_array_equal(nat[i], py[i])
    nat.close()


def test_batch_gather_and_prefetch(token_file):
    py = TokenDataset(token_file, seq_len=32)
    nat = native_loader.NativeTokenDataset(token_file, seq_len=32)
    idx = np.asarray([5, 0, 99, 42], np.int64)
    want = np.stack([py[int(i)] for i in idx])
    np.testing.assert_array_equal(nat.gather(idx), want)
    # background prefetch returns the same bytes
    nat.prefetch(idx)
    np.testing.assert_array_equal(nat.wait(), want)
    # pipelined: post next while consuming current
    nat.prefetch(idx[::-1].copy())
    np.testing.assert_array_equal(nat.wait(), want[::-1])
    nat.close()


@pytest.mark.parametrize(
    "dtype", [np.int8, np.int16, np.int32, np.int64, np.uint8, np.uint16]
)
def test_token_widths(tmp_path, dtype):
    path = str(tmp_path / "t.npy")
    lo = 0 if np.dtype(dtype).kind == "u" else -7
    toks = np.arange(lo, 250 + lo, dtype=dtype)
    if np.dtype(dtype) == np.uint16:
        toks = toks + 40000  # beyond int16 range: catches sign-extension bugs
    write_token_file(path, toks)
    nat = native_loader.NativeTokenDataset(path, seq_len=10)
    np.testing.assert_array_equal(nat[0], toks[:10].astype(np.int32))
    np.testing.assert_array_equal(nat[24], toks[240:250].astype(np.int32))
    nat.close()


def test_rejects_2d(tmp_path):
    path = str(tmp_path / "bad.npy")
    np.save(path, np.zeros((4, 4), np.int32))
    with pytest.raises(ValueError):
        native_loader.NativeTokenDataset(path, seq_len=2)


def test_distributed_loader_native_prefetch_parity(token_file):
    """DistributedDataLoader over the native dataset (prefetch path) yields
    byte-identical batches to the numpy dataset, including across resume."""
    from neuronx_distributed_llama3_2_tpu.data.dataset import (
        DistributedDataLoader,
        LoaderState,
    )

    py = DistributedDataLoader(TokenDataset(token_file, 32), 8, seed=3)
    nat_ds = native_loader.NativeTokenDataset(token_file, 32)
    nat = DistributedDataLoader(nat_ds, 8, seed=3)
    it_py, it_nat = iter(py), iter(nat)
    for _ in range(5):
        np.testing.assert_array_equal(next(it_nat), next(it_py))
    # resume from step 3 replays the same stream
    nat2 = DistributedDataLoader(
        native_loader.NativeTokenDataset(token_file, 32), 8, seed=3,
        state=LoaderState(step=3),
    )
    np.testing.assert_array_equal(next(iter(nat2)), py.batch_at(3))
    nat_ds.close()
