"""S3 checkpoint-storage tests against an in-memory fake boto3 (the
reference unit-tests checkpoint storage the same mock-based way,
test_checkpoint_storage.py). Exercises the real S3CheckpointStorage code
paths: key layout, pagination, 404-vs-error discrimination, marker
protocol, and a full save/load/copy checkpoint lifecycle."""

import io
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest


class _ClientError(Exception):
    def __init__(self, status=404, code="NoSuchKey"):
        self.response = {
            "ResponseMetadata": {"HTTPStatusCode": status},
            "Error": {"Code": code},
        }


class _FakeS3Client:
    PAGE = 2  # tiny page size so pagination paths actually paginate

    def __init__(self, store):
        self.store = store

    def put_object(self, Bucket, Key, Body):
        if hasattr(Body, "read"):
            Body = Body.read()
        if isinstance(Body, str):
            Body = Body.encode()
        self.store[(Bucket, Key)] = bytes(Body)

    def get_object(self, Bucket, Key):
        if (Bucket, Key) not in self.store:
            raise _ClientError()
        return {"Body": io.BytesIO(self.store[(Bucket, Key)])}

    def head_object(self, Bucket, Key):
        if (Bucket, Key) not in self.store:
            raise _ClientError()
        return {}

    def delete_object(self, Bucket, Key):
        self.store.pop((Bucket, Key), None)

    def delete_objects(self, Bucket, Delete):
        for o in Delete["Objects"]:
            self.store.pop((Bucket, o["Key"]), None)

    def list_objects_v2(self, Bucket, Prefix, MaxKeys=1000, Delimiter=None,
                        ContinuationToken=None):
        keys = sorted(
            k for (b, k) in self.store if b == Bucket and k.startswith(Prefix)
        )
        contents, prefixes = [], []
        for k in keys:
            rest = k[len(Prefix):]
            if Delimiter and Delimiter in rest:
                cp = Prefix + rest.split(Delimiter)[0] + Delimiter
                if cp not in prefixes:
                    prefixes.append(cp)
            else:
                contents.append({"Key": k})
        start = int(ContinuationToken or 0)
        page_c = contents[start : start + MaxKeys]
        resp = {
            "KeyCount": len(page_c) + len(prefixes),
            "Contents": page_c,
            "CommonPrefixes": [{"Prefix": p} for p in prefixes],
        }
        if start + MaxKeys < len(contents):
            resp["IsTruncated"] = True
            resp["NextContinuationToken"] = str(start + MaxKeys)
        return resp

    def get_paginator(self, op):
        assert op == "list_objects_v2"
        client = self

        class _P:
            def paginate(self, **kw):
                kw.setdefault("MaxKeys", client.PAGE)
                # snapshot pages up front (real S3 pagination is stable
                # against deletes of already-listed keys; a live view would
                # skip keys when the caller deletes while paginating)
                pages = []
                token = None
                while True:
                    resp = client.list_objects_v2(ContinuationToken=token, **kw)
                    pages.append(resp)
                    if not resp.get("IsTruncated"):
                        break
                    token = resp["NextContinuationToken"]
                yield from pages

        return _P()


@pytest.fixture()
def fake_s3(monkeypatch):
    store = {}
    fake_boto3 = types.ModuleType("boto3")
    fake_boto3.client = lambda name: _FakeS3Client(store)
    fake_botocore = types.ModuleType("botocore")
    fake_botocore.exceptions = types.SimpleNamespace(ClientError=_ClientError)
    monkeypatch.setitem(sys.modules, "boto3", fake_boto3)
    monkeypatch.setitem(sys.modules, "botocore", fake_botocore)
    return store


def test_s3_storage_primitives(fake_s3):
    from neuronx_distributed_llama3_2_tpu.checkpoint.storage import (
        create_checkpoint_storage,
    )

    st = create_checkpoint_storage("s3://bucket/ckpts/run1")
    assert type(st).__name__ == "S3CheckpointStorage"
    assert not st.file_exists("x")
    st.save_text("hello", "x")
    assert st.file_exists("x")
    assert st.load_text("x") == "hello"
    st.save_bytes(b"\x00\x01", "tag/a/b.npy")
    assert st.dir_exists("tag")
    # listdir sees both subdirs and files, across pagination pages
    for i in range(5):
        st.save_text(str(i), f"tag/f{i}")
    names = st.listdir("tag")
    assert "a" in names and {f"f{i}" for i in range(5)} <= set(names)
    st.remove_dir("tag")
    assert not st.dir_exists("tag")
    assert st.file_exists("x")  # sibling untouched
    st.remove_file("x")
    assert not st.file_exists("x")


def test_s3_non_404_errors_propagate(fake_s3):
    """Throttling/5xx must NOT read as 'file missing' — the done-marker GC
    would delete valid checkpoints (storage.py:208-217)."""
    from neuronx_distributed_llama3_2_tpu.checkpoint.storage import (
        create_checkpoint_storage,
    )

    st = create_checkpoint_storage("s3://bucket/p")
    orig = st._client.head_object

    def throttled(Bucket, Key):
        raise _ClientError(status=503, code="SlowDown")

    st._client.head_object = throttled
    with pytest.raises(_ClientError):
        st.file_exists("anything")
    st._client.head_object = orig


def test_s3_checkpoint_lifecycle(fake_s3):
    """save → markers → load → copy_checkpoint fs↔s3, end to end on the
    fake client."""
    from neuronx_distributed_llama3_2_tpu.checkpoint import (
        copy_checkpoint,
        load_checkpoint,
        save_checkpoint,
    )

    tree = {
        "w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        "b": jnp.ones((3,), jnp.bfloat16),
    }
    save_checkpoint("s3://bucket/ckpts", tag="t1", model=tree,
                    user_content={"note": "s3"})
    loaded = load_checkpoint(
        "s3://bucket/ckpts", tag="latest", model=jax.eval_shape(lambda: tree)
    )
    np.testing.assert_array_equal(np.asarray(loaded["model"]["w"]), np.asarray(tree["w"]))
    assert loaded["user_content"] == {"note": "s3"}
    # offline copy from S3 to S3 (the copy-tag CLI path over the S3 backend)
    copy_checkpoint("s3://bucket/ckpts", "t1", "s3://bucket/export", "t1x")
    again = load_checkpoint(
        "s3://bucket/export", tag="t1x", model=jax.eval_shape(lambda: tree)
    )
    np.testing.assert_array_equal(
        np.asarray(again["model"]["b"], np.float32),
        np.asarray(tree["b"], np.float32),
    )
