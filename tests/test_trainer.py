"""Trainer tests: optimizer math vs torch AdamW golden, ZeRO-1 sharding specs,
grad-accum equivalence, end-to-end loss decrease on the mesh.

Mirrors the reference's optimizer/wrapper unit tiers
(test/unit_test/wrapper/test_optimizer_wrapper.py, zero1 tests) run on the
8-device CPU mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from neuronx_distributed_llama3_2_tpu.models.llama import LLAMA_CONFIGS, LlamaForCausalLM
from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state
from neuronx_distributed_llama3_2_tpu.trainer import (
    OptimizerConfig,
    TrainingConfig,
    apply_gradients,
    init_optimizer_state,
    initialize_parallel_model,
    make_train_step,
    optimizer_state_specs,
)

TINY = LLAMA_CONFIGS["tiny"]


def _opt_cfg(**kw):
    kw.setdefault("warmup_steps", 0)
    kw.setdefault("schedule", "constant")
    return OptimizerConfig(**kw)


def test_adamw_matches_torch():
    """Our fp32-master AdamW step == torch.optim.AdamW (the reference's
    AdamW_FP32OptimParams is torch AdamW + fp32 state,
    utils/adamw_fp32_optim_params.py:31)."""
    import torch

    cfg = _opt_cfg(
        learning_rate=1e-2, weight_decay=0.1, grad_clipping=False
    )
    rng = np.random.default_rng(0)
    w = rng.standard_normal((4, 8)).astype(np.float32)
    g = rng.standard_normal((4, 8)).astype(np.float32)

    tw = torch.nn.Parameter(torch.from_numpy(w.copy()))
    opt = torch.optim.AdamW(
        [tw], lr=1e-2, betas=(cfg.beta1, cfg.beta2), eps=cfg.eps,
        weight_decay=0.1,
    )
    params = {"w": jnp.asarray(w)}
    state = init_optimizer_state(params, cfg)
    grads = {"w": jnp.asarray(g)}
    for _ in range(5):
        tw.grad = torch.from_numpy(g.copy())
        opt.step()
        params, state, _ = apply_gradients(state, grads, params, cfg)
    np.testing.assert_allclose(
        np.asarray(params["w"]), tw.detach().numpy(), rtol=1e-5, atol=1e-6
    )


def test_master_weights_bf16():
    """bf16 params track the fp32 master exactly (cast), and tiny updates are
    not lost to bf16 rounding (reference use_master_weights semantics)."""
    cfg = _opt_cfg(learning_rate=1e-5, weight_decay=0.0, grad_clipping=False)
    params = {"w": jnp.ones((8,), jnp.bfloat16)}
    state = init_optimizer_state(params, cfg)
    grads = {"w": jnp.full((8,), 1e-3, jnp.bfloat16)}
    for _ in range(10):
        params, state, _ = apply_gradients(state, grads, params, cfg)
    # master moved ~10*lr; a pure-bf16 param would have swallowed each step
    assert float(jnp.max(jnp.abs(state.master["w"] - 1.0))) > 5e-5
    np.testing.assert_array_equal(
        np.asarray(params["w"]),
        np.asarray(state.master["w"].astype(jnp.bfloat16)),
    )


def test_zero1_specs():
    parallel_state.initialize_model_parallel(tensor_model_parallel_size=2)
    # mesh: pp=1 dp=4 ep=1 tp=2 → dp_total=4
    params = {
        "a": jnp.zeros((8, 6)),   # dim0 divisible by 4
        "b": jnp.zeros((3, 6)),   # nothing divisible → stays as param spec
        "c": jnp.zeros((4, 8)),   # dim0 sharded by tp → dp goes to dim1
    }
    pspecs = {"a": P(None, None), "b": P(None, None), "c": P("tp", None)}
    sspecs = optimizer_state_specs(pspecs, params, _opt_cfg())
    assert sspecs.mu["a"] == P(("dp", "ep"), None)
    assert sspecs.mu["b"] == P(None, None)
    assert sspecs.mu["c"] == P("tp", ("dp", "ep"))
    assert sspecs.master["a"] == sspecs.mu["a"]
    # zero1 off → state specs == param specs
    off = optimizer_state_specs(pspecs, params, _opt_cfg(zero_one_enabled=False))
    assert off.mu == pspecs


@pytest.mark.slow
def test_grad_accum_equivalence():
    """num_microbatches=4 produces the same step as one full batch
    (reference grad-accum semantics)."""
    parallel_state.initialize_model_parallel()
    model = LlamaForCausalLM(TINY)
    rng = np.random.default_rng(5)
    ids = jnp.asarray(rng.integers(0, TINY.vocab_size, (8, 16), dtype=np.int32))
    batch = {"input_ids": ids, "labels": ids}

    cfg1 = TrainingConfig(num_microbatches=1, optimizer=_opt_cfg())
    cfg4 = TrainingConfig(num_microbatches=4, optimizer=_opt_cfg())
    state1, _ = initialize_parallel_model(model, cfg1)
    state4, _ = initialize_parallel_model(model, cfg4)

    new1, m1 = make_train_step(model, cfg1)(state1, batch)
    new4, m4 = make_train_step(model, cfg4)(state4, batch)
    assert abs(float(m1["loss"]) - float(m4["loss"])) < 1e-5
    for a, b in zip(jax.tree.leaves(new1.params), jax.tree.leaves(new4.params)):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32),
            np.asarray(b, dtype=np.float32),
            atol=2e-5,
        )


@pytest.mark.parametrize("zero1", [True, False])
def test_train_loop_loss_decreases(zero1):
    """End-to-end: tp=2 dp=2(+zero1) training memorizes a fixed batch
    (reference convergence smoke, test_bert_pretraining.py pattern)."""
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, sequence_parallel=True
    )
    cfg = TrainingConfig(
        tensor_parallel_size=2,
        sequence_parallel=True,
        num_microbatches=2,
        optimizer=_opt_cfg(learning_rate=3e-3, zero_one_enabled=zero1),
    )
    model = LlamaForCausalLM(TINY)
    state, specs = initialize_parallel_model(model, cfg)
    # verify zero1 placement actually happened
    mu_shard = jax.tree.leaves(specs.opt.mu)[0]
    step = make_train_step(model, cfg)
    rng = np.random.default_rng(9)
    ids = jnp.asarray(rng.integers(0, TINY.vocab_size, (8, 32), dtype=np.int32))
    batch = {"input_ids": ids, "labels": ids}
    losses = []
    for _ in range(10):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses
    assert np.isfinite(losses).all()


def test_lr_schedule():
    cfg = OptimizerConfig(
        learning_rate=1.0, warmup_steps=10, total_steps=110,
        min_lr_ratio=0.1, schedule="cosine",
    )
    assert float(cfg.lr_at(0)) == 0.0
    assert abs(float(cfg.lr_at(10)) - 1.0) < 1e-6
    assert abs(float(cfg.lr_at(110)) - 0.1) < 1e-6
    lin = dataclasses.replace(cfg, schedule="linear")
    assert abs(float(lin.lr_at(60)) - (0.1 + 0.9 * 0.5)) < 1e-6


def test_eval_step_matches_loss_and_no_param_change():
    """make_eval_step (reference run_eval/InferenceSchedule role): same loss
    as model.loss, params untouched, works under tp + pp."""
    import dataclasses

    from neuronx_distributed_llama3_2_tpu.models.llama import (
        LLAMA_CONFIGS,
        LlamaForCausalLM,
    )
    from neuronx_distributed_llama3_2_tpu.pipeline import PipelinedCausalLM
    from neuronx_distributed_llama3_2_tpu.trainer import (
        evaluate,
        make_eval_step,
    )

    parallel_state.destroy_model_parallel()
    cfg = TrainingConfig(
        tensor_parallel_size=2, pipeline_parallel_size=2,
        optimizer=OptimizerConfig(zero_one_enabled=True, warmup_steps=1),
    )
    cfg.initialize(devices=jax.devices()[:8])
    try:
        tiny = LLAMA_CONFIGS["tiny"]
        base = LlamaForCausalLM(tiny)
        model = PipelinedCausalLM(base, num_microbatches=2)
        state, _ = initialize_parallel_model(model, cfg)
        ids = jnp.asarray(
            np.random.default_rng(3).integers(0, tiny.vocab_size, (8, 32)),
            jnp.int32,
        )
        batch = {"input_ids": ids, "labels": ids}
        step = make_eval_step(model, cfg)
        got = float(step(state.params, batch))
        want = float(jax.jit(model.loss)(state.params, ids, ids))
        np.testing.assert_allclose(got, want, rtol=1e-6)
        mean = evaluate(model, cfg, state.params, [batch, batch])
        np.testing.assert_allclose(mean, want, rtol=1e-6)
    finally:
        parallel_state.destroy_model_parallel()


def test_cast_params_downcast_keeps_norms_fp32():
    """utils.casting.cast_params: the reference's float32→bf16 serving cast
    with the lm-head/norm fp32 exception list (model_wrapper.py:303)."""
    import dataclasses

    from neuronx_distributed_llama3_2_tpu.models.llama import (
        LLAMA_CONFIGS,
        LlamaForCausalLM,
    )
    from neuronx_distributed_llama3_2_tpu.utils.casting import cast_params

    # untied config so the lm_head exception is actually exercised
    cfg = dataclasses.replace(LLAMA_CONFIGS["tiny"], tie_word_embeddings=False)
    params = LlamaForCausalLM(cfg).init(jax.random.key(0))
    cast = cast_params(params, jnp.bfloat16)
    # norms + lm head stay fp32 (the reference exception list)
    assert cast["final_norm"]["scale"].dtype == jnp.float32
    assert cast["layers"]["attn_norm"]["scale"].dtype == jnp.float32
    assert cast["lm_head"]["kernel"].dtype == jnp.float32
    # matmul weights downcast
    assert cast["layers"]["attn"]["qkv"]["q_kernel"].dtype == jnp.bfloat16
    assert cast["embed"]["embedding"].dtype == jnp.bfloat16
    # bf16 model runs with the cast tree
    bf_cfg = dataclasses.replace(cfg, dtype=jnp.bfloat16, tie_word_embeddings=False)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8)), jnp.int32
    )
    out = LlamaForCausalLM(bf_cfg)(cast, ids)
    assert np.isfinite(np.asarray(out, np.float32)).all()
    # int8 quantized payloads untouched
    from neuronx_distributed_llama3_2_tpu.quantization import quantize_params

    q = cast_params(quantize_params(params), jnp.bfloat16)
    assert q["layers"]["attn"]["qkv"]["q_kernel"].qvalue.dtype == jnp.int8
    # the dequant scale must STAY fp32 (a bf16 scale would smear ~0.4%
    # relative error over every dequantized weight)
    assert q["layers"]["attn"]["qkv"]["q_kernel"].scale.dtype == jnp.float32


def test_pipeline_config_mismatch_fails_loudly():
    """TrainingConfig(pipeline_schedule=...) must never be silently ignored:
    a mismatch with the model's actual schedule raises (ADVICE r3)."""
    from neuronx_distributed_llama3_2_tpu.pipeline import PipelinedCausalLM
    from neuronx_distributed_llama3_2_tpu.trainer import (
        TrainingConfig,
        make_train_step,
    )

    cfg = TrainingConfig(
        pipeline_parallel_size=2, pipeline_schedule="interleaved",
        num_model_chunks=2,
    )
    cfg.initialize()
    try:
        # unpipelined model + pipeline knobs set -> loud failure
        with pytest.raises(ValueError, match="not pipelined"):
            make_train_step(LlamaForCausalLM(TINY), cfg)
        # pipelined model with a DIFFERENT schedule -> loud failure
        gp = PipelinedCausalLM(LlamaForCausalLM(TINY), num_microbatches=4)
        with pytest.raises(ValueError, match="schedule"):
            make_train_step(gp, cfg)
        # chunk-count mismatch -> loud failure
        il = PipelinedCausalLM(
            LlamaForCausalLM(TINY), num_microbatches=4,
            schedule="interleaved", num_model_chunks=4,
        )
        with pytest.raises(ValueError, match="num_model_chunks"):
            make_train_step(il, cfg)
        # None knobs follow the model: no raise
        make_train_step(gp, TrainingConfig(pipeline_parallel_size=2))
    finally:
        parallel_state.destroy_model_parallel()
