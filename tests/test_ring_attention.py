"""Ring attention / context parallelism tests (first-class long-context
strategy — no reference analogue; the reference stops at SP, SURVEY §2.10)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_llama3_2_tpu.kernels.flash_attention import (
    flash_attention_reference,
)
from neuronx_distributed_llama3_2_tpu.kernels.ring_attention import (
    ring_attention_sharded,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
    core_attention,
)
from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state
from neuronx_distributed_llama3_2_tpu.parallel.layers import shard_pytree


def _qkv(s=128, n=4, nkv=2, d=32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((2, s, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, s, nkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, s, nkv, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(causal):
    st = parallel_state.initialize_model_parallel(context_parallel_size=4)
    q, k, v = _qkv()
    ref = core_attention(q, k, v, causal=causal)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, st.mesh, parallel_state.CP_AXIS, causal=causal
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_match():
    st = parallel_state.initialize_model_parallel(context_parallel_size=4)
    q, k, v = _qkv(s=64)

    def lp(q, k, v):
        return (
            ring_attention_sharded(
                q, k, v, st.mesh, parallel_state.CP_AXIS, causal=True
            ) ** 2
        ).sum()

    def lr(q, k, v):
        return (core_attention(q, k, v, causal=True) ** 2).sum()

    gp = jax.jit(jax.grad(lp, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_ring_sharded_inputs_stay_sharded():
    """With S actually device-sharded over cp, each step moves only the
    local k/v chunk (the O(S/cp) memory property)."""
    st = parallel_state.initialize_model_parallel(context_parallel_size=8)
    q, k, v = _qkv(s=256)
    spec = NamedSharding(st.mesh, P(None, parallel_state.CP_AXIS, None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, st.mesh, parallel_state.CP_AXIS, causal=True
        )
    )(qs, ks, vs)
    assert out.sharding.spec[1] == parallel_state.CP_AXIS
    ref = core_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_with_tp_combined():
    """cp=2 x tp=2: ring over cp while heads stay tp-shardable (auto)."""
    st = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, context_parallel_size=2
    )
    q, k, v = _qkv(s=64)
    ref = core_attention(q, k, v, causal=True)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, st.mesh, parallel_state.CP_AXIS, causal=True
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_llama_forward_with_cp():
    """Full model parity: cp=2 x tp=2 llama forward == unsharded."""
    cfg = LLAMA_CONFIGS["tiny"]
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 32)), jnp.int32
    )
    ref = jax.jit(model.__call__)(params, ids)
    ref_loss = jax.jit(model.loss)(params, ids, ids)

    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, context_parallel_size=2
    )
    sharded = shard_pytree(params, model.specs())
    out = jax.jit(model.__call__)(sharded, ids)
    loss = jax.jit(model.loss)(sharded, ids, ids)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4, rtol=1e-4
    )
    assert abs(float(loss) - float(ref_loss)) < 1e-4


@pytest.mark.slow
def test_llama_train_step_with_cp():
    """cp=2 training through the trainer facade: grads match cp=1."""
    from neuronx_distributed_llama3_2_tpu.trainer import (
        OptimizerConfig,
        TrainingConfig,
        initialize_parallel_model,
        make_train_step,
    )

    cfg = LLAMA_CONFIGS["tiny"]
    ids = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (4, 32)), jnp.int32
    )
    tc = TrainingConfig(
        context_parallel_size=2,
        tensor_parallel_size=2,
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=1),
    )
    tc.initialize()
    model = LlamaForCausalLM(cfg)
    state, _ = initialize_parallel_model(model, tc)
    step = make_train_step(model, tc)
    losses = []
    for _ in range(4):
        state, m = step(state, {"input_ids": ids, "labels": ids})
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


@pytest.mark.parametrize("causal", [True, False])
def test_ring_multiblock_and_padded_tail(causal):
    """block_kv smaller than (and not dividing) the chunk: nblk>1 with a
    padded tail block — the branches a single-block test never touches."""
    q, k, v = _qkv(s=80)  # 40 per chunk; block_kv=16 -> 3 blocks, pad=8
    ref = core_attention(q, k, v, causal=causal)  # oracle before mesh init
    st = parallel_state.initialize_model_parallel(context_parallel_size=2)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, st.mesh, parallel_state.CP_AXIS, causal=causal,
            block_kv=16,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_multiblock_grads():
    q, k, v = _qkv(s=80)

    def lr(q, k, v):
        return (core_attention(q, k, v, causal=True) ** 2).sum()

    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)  # oracle before mesh init
    st = parallel_state.initialize_model_parallel(context_parallel_size=2)

    def lp(q, k, v):
        return (
            ring_attention_sharded(
                q, k, v, st.mesh, parallel_state.CP_AXIS, causal=True,
                block_kv=16,
            ) ** 2
        ).sum()

    gp = jax.jit(jax.grad(lp, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


# ---------------------------------------------------------------------------
# Pallas-fused ring executors (interpret mode on the CPU mesh)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl", ["pallas", "zigzag"])
@pytest.mark.parametrize("causal", [True, False])
def test_pallas_ring_matches_dense(impl, causal):
    """The Pallas-fused ring (contiguous and zigzag-balanced) matches dense
    attention — fwd. Interpret mode: same kernel code path as TPU, minus
    Mosaic lowering."""
    if impl == "zigzag" and not causal:
        pytest.skip("zigzag only defined for causal")
    st = parallel_state.initialize_model_parallel(context_parallel_size=4)
    q, k, v = _qkv()
    ref = core_attention(q, k, v, causal=causal)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, st.mesh, parallel_state.CP_AXIS, causal=causal,
            impl=impl,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("impl", ["pallas", "zigzag"])
def test_pallas_ring_gradients_match(impl):
    """Custom-VJP ring backward (per-chunk Pallas dq/dkv with global lse,
    dk/dv rotating home) matches dense autodiff."""
    st = parallel_state.initialize_model_parallel(context_parallel_size=4)
    q, k, v = _qkv(s=64)

    def lp(q, k, v):
        return (
            ring_attention_sharded(
                q, k, v, st.mesh, parallel_state.CP_AXIS, causal=True,
                impl=impl,
            ).astype(jnp.float32) ** 2
        ).sum()

    def lr(q, k, v):
        return (core_attention(q, k, v, causal=True) ** 2).sum()

    gp = jax.jit(jax.grad(lp, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gp, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5,
            err_msg=f"d{name} mismatch ({impl})",
        )


def test_pallas_ring_gqa_matches_jnp_ring():
    """GQA (n != nkv) through the pallas ring == the jnp ring oracle."""
    st = parallel_state.initialize_model_parallel(context_parallel_size=4)
    q, k, v = _qkv(s=128, n=8, nkv=2, seed=3)
    ref = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, st.mesh, parallel_state.CP_AXIS, causal=True, impl="jnp"
        )
    )(q, k, v)
    for impl in ("pallas", "zigzag"):
        out = jax.jit(
            lambda q, k, v: ring_attention_sharded(
                q, k, v, st.mesh, parallel_state.CP_AXIS, causal=True,
                impl=impl,
            )
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, err_msg=impl
        )


def test_zigzag_permutation_roundtrip():
    from neuronx_distributed_llama3_2_tpu.kernels.ring_attention_pallas import (
        zigzag_permutation,
    )

    perm, inv = zigzag_permutation(32, 4)
    x = jnp.arange(32)
    np.testing.assert_array_equal(np.asarray(x.take(perm).take(inv)), np.asarray(x))
    # device 0 holds half-chunks (0, 7) of the 8-way split
    np.testing.assert_array_equal(
        np.asarray(x.take(perm)[:8]),
        np.concatenate([np.arange(0, 4), np.arange(28, 32)]),
    )


@pytest.mark.slow  # tier-1 time budget; cheaper siblings cover this path
def test_model_level_zigzag_matches_contiguous():
    """cp_ring_layout='zigzag': the backbone permutes ONCE outside the layer
    stack (no per-attention-call shuffles), declares the layout via
    cp_layout(), and the loss matches the contiguous path. Both layouts run
    inside this one test so the comparison cannot be skipped by test
    selection/ordering."""
    import dataclasses

    from neuronx_distributed_llama3_2_tpu.trainer import (
        OptimizerConfig,
        TrainingConfig,
        initialize_parallel_model,
        make_train_step,
    )

    results = {}
    for layout in ("contiguous", "zigzag"):
        parallel_state.destroy_model_parallel()
        tc = TrainingConfig(
            context_parallel_size=4,
            tensor_parallel_size=2,
            optimizer=OptimizerConfig(zero_one_enabled=True, warmup_steps=1),
        )
        tc.initialize()
        cfg = dataclasses.replace(
            LLAMA_CONFIGS["tiny"], max_seq_len=128, cp_ring_layout=layout
        )
        model = LlamaForCausalLM(cfg)
        state, _ = initialize_parallel_model(model, tc)
        step = make_train_step(model, tc)
        ids = jnp.asarray(
            np.random.default_rng(11).integers(0, cfg.vocab_size, (4, 128)),
            jnp.int32,
        )
        state, m = step(state, {"input_ids": ids, "labels": ids})
        results[layout] = (float(m["loss"]), float(m["grad_norm"]))
        assert np.isfinite(results[layout][0])
    ref, zz = results["contiguous"], results["zigzag"]
    assert abs(zz[0] - ref[0]) / ref[0] < 1e-4, results
    assert abs(zz[1] - ref[1]) / ref[1] < 1e-3, results
    parallel_state.destroy_model_parallel()


@pytest.mark.slow  # tier-1 time budget; cheaper siblings cover this path
def test_gpipe_cp_zigzag_trains():
    """pp=2 x cp=2 gpipe with forced zigzag: the pipeline executor permutes
    once, the per-layer ring runs pre-permuted, loss finite and equal to
    the contiguous run."""
    import dataclasses

    from neuronx_distributed_llama3_2_tpu.pipeline import PipelinedCausalLM
    from neuronx_distributed_llama3_2_tpu.trainer import (
        OptimizerConfig,
        TrainingConfig,
        initialize_parallel_model,
        make_train_step,
    )

    losses = {}
    for layout in ("contiguous", "zigzag"):
        parallel_state.destroy_model_parallel()
        tc = TrainingConfig(
            pipeline_parallel_size=2,
            context_parallel_size=2,
            optimizer=OptimizerConfig(zero_one_enabled=True, warmup_steps=1),
        )
        tc.initialize()
        cfg = dataclasses.replace(
            LLAMA_CONFIGS["tiny"], max_seq_len=64, cp_ring_layout=layout
        )
        model = PipelinedCausalLM(
            LlamaForCausalLM(cfg), num_microbatches=2, schedule="gpipe"
        )
        state, _ = initialize_parallel_model(model, tc)
        step = make_train_step(model, tc)
        ids = jnp.asarray(
            np.random.default_rng(13).integers(0, cfg.vocab_size, (4, 64)),
            jnp.int32,
        )
        state, m = step(state, {"input_ids": ids, "labels": ids})
        losses[layout] = float(m["loss"])
        assert np.isfinite(losses[layout])
    rel = abs(losses["zigzag"] - losses["contiguous"]) / losses["contiguous"]
    assert rel < 1e-4, losses
    parallel_state.destroy_model_parallel()
