"""MoE tests.

Mirror of the reference's MoE test strategy (SURVEY.md §4): golden-model
comparison against a dense per-token reference implementation
(test/unit_test/modules/moe/test_impl_correctness.py:40-46 — there with bf16
tolerances; here fp32 so much tighter), EP device-correctness on the virtual
mesh (test/integration/modules/moe/device_correctness_test_runner.py), and
router/loss unit tests.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.moe import (
    ExpertMLPs,
    MoE,
    MoEConfig,
    load_balancing_loss,
    sinkhorn,
    sinkhorn_routing,
    top_k_routing,
)
from neuronx_distributed_llama3_2_tpu.models.mixtral import (
    MIXTRAL_CONFIGS,
    MixtralForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state
from neuronx_distributed_llama3_2_tpu.parallel.layers import shard_pytree

CFG = MoEConfig(
    hidden_size=32, intermediate_size=64, num_experts=4, top_k=2,
    dtype=jnp.float32,
)


def _dense_reference(params, x, gates, idx, glu=True):
    """Per-token loop: the golden model (reference sbase_model.py role)."""
    t, h = x.shape
    out = np.zeros((t, h), np.float32)
    gate_up = np.asarray(params["gate_up"], np.float32)
    down = np.asarray(params["down"], np.float32)
    x = np.asarray(x, np.float32)
    gates = np.asarray(gates, np.float32)
    idx = np.asarray(idx)
    for ti in range(t):
        for ki in range(idx.shape[1]):
            e = int(idx[ti, ki])
            h1 = np.einsum("h,hti->ti", x[ti], gate_up[e])  # (2, I)
            act = (h1[0] / (1 + np.exp(-h1[0]))) * h1[1] if glu else None
            out[ti] += gates[ti, ki] * (act @ down[e])
    return out


def test_top_k_routing():
    logits = jnp.asarray(
        [[1.0, 3.0, 2.0, 0.0], [0.0, 0.0, 5.0, 4.0]], jnp.float32
    )
    gates, idx = top_k_routing(logits, 2, normalize=True)
    np.testing.assert_array_equal(np.asarray(idx), [[1, 2], [2, 3]])
    np.testing.assert_allclose(np.asarray(gates).sum(-1), 1.0, rtol=1e-6)
    gates_un, _ = top_k_routing(logits, 2, normalize=False)
    assert float(gates_un.sum(-1)[0]) < 1.0


def test_sinkhorn_balances():
    """Sinkhorn-normalized matrix is ~doubly stochastic; a degenerate router
    (all tokens prefer expert 0) gets spread across experts."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(16, 4)) * 0.1, jnp.float32)
    logits = logits.at[:, 0].add(5.0)  # degenerate preference
    balanced = sinkhorn(logits, n_iters=10)
    col_mass = np.asarray(balanced.sum(0))
    assert col_mass.std() / col_mass.mean() < 0.05  # near-uniform columns
    gates, idx = sinkhorn_routing(logits, 1, n_iters=10)
    counts = np.bincount(np.asarray(idx).ravel(), minlength=4)
    assert counts.max() <= 8  # plain top-1 would put all 16 on expert 0


def test_load_balancing_loss():
    rng = np.random.default_rng(1)
    uniform = jnp.zeros((64, 4), jnp.float32)
    idx = jnp.asarray(rng.integers(0, 4, (64, 2)), jnp.int32)
    # uniform probs + uniform assignment -> loss == 1.0 (perfect balance)
    balanced_idx = jnp.stack(
        [jnp.arange(64, dtype=jnp.int32) % 4,
         (jnp.arange(64, dtype=jnp.int32) + 1) % 4], axis=1
    )
    assert abs(float(load_balancing_loss(uniform, balanced_idx, 4)) - 1.0) < 1e-5
    # collapse onto one expert -> loss > 1
    collapsed = jnp.full((64, 2), 0, jnp.int32)
    peaked = jnp.zeros((64, 4), jnp.float32).at[:, 0].add(10.0)
    assert float(load_balancing_loss(peaked, collapsed, 4)) > 2.0


def test_expert_mlps_match_dense_reference():
    """Both dispatch paths vs the per-token golden loop (reference
    test_impl_correctness.py pattern; fp32 so atol is tight)."""
    rng = np.random.default_rng(2)
    mlps = ExpertMLPs(
        num_experts=4, hidden_size=32, intermediate_size=64,
        capacity_factor=None, dtype=jnp.float32,
    )
    params = mlps.init(jax.random.key(0))
    x = jnp.asarray(rng.normal(size=(24, 32)), jnp.float32)
    logits = jnp.asarray(rng.normal(size=(24, 4)), jnp.float32)
    gates, idx = top_k_routing(logits, 2)
    want = _dense_reference(params, x, gates, idx)

    got_all = mlps.forward_all_experts(params, x, gates, idx)
    np.testing.assert_allclose(np.asarray(got_all), want, atol=1e-5)

    ample = dataclasses.replace(mlps, capacity_factor=4.0)  # no dropping
    got_cap = ample.forward_capacity_factor(params, x, gates, idx)
    np.testing.assert_allclose(np.asarray(got_cap), want, atol=1e-5)


def test_capacity_dropping():
    """Tokens beyond capacity are dropped token-major (earlier tokens win) —
    reference forward_capacity_factor semantics (expert_mlps.py:169)."""
    mlps = ExpertMLPs(
        num_experts=2, hidden_size=8, intermediate_size=16,
        capacity_factor=0.5, dtype=jnp.float32,
    )
    params = mlps.init(jax.random.key(0))
    t = 8
    x = jnp.asarray(np.random.default_rng(3).normal(size=(t, 8)), jnp.float32)
    # all tokens choose expert 0 (top-1): capacity = ceil(8*1*0.5/2) = 2
    gates = jnp.ones((t, 1), jnp.float32)
    idx = jnp.zeros((t, 1), jnp.int32)
    out = mlps.forward_capacity_factor(params, x, gates, idx)
    kept = np.abs(np.asarray(out)).sum(-1) > 1e-9
    np.testing.assert_array_equal(kept, [True, True] + [False] * 6)


def test_moe_block_and_grads():
    moe = MoE(CFG)
    params = moe.init(jax.random.key(0))
    x = jnp.asarray(
        np.random.default_rng(4).normal(size=(2, 8, 32)), jnp.float32
    )
    y, logits, idx = moe(params, x)
    assert y.shape == x.shape
    assert logits.shape == (16, 4) and idx.shape == (16, 2)

    def loss_fn(p):
        y, lg, ix = moe(p, x)
        return jnp.mean(y ** 2) + 0.01 * load_balancing_loss(lg, ix, 4)

    grads = jax.jit(jax.grad(loss_fn))(params)
    for g in jax.tree.leaves(grads):
        assert bool(jnp.all(jnp.isfinite(g)))
    # router must receive gradient through the gate values
    assert float(jnp.abs(grads["router"]["kernel"]).max()) > 0


def test_ep_requires_capacity_factor():
    """ep>1 with the all-experts (no-drop) dispatch is an explicit error —
    it would buffer T·top_k slots per expert."""
    moe = MoE(CFG)  # capacity_factor=None
    params = moe.init(jax.random.key(0))
    parallel_state.initialize_model_parallel(expert_model_parallel_size=2)
    x = jnp.zeros((4, 8, 32), jnp.float32)
    with pytest.raises(ValueError, match="capacity_factor"):
        moe(params, x)


@pytest.mark.parametrize("capacity_factor", [8.0])
def test_ep_parity(capacity_factor):
    """tp=2 × ep=2 × dp=2 sharded MoE (explicit a2a path) == single-device
    (ample capacity so per-shard dropping can't diverge) — the reference's
    EP device-correctness gate (test_ep.py role)."""
    cfg = dataclasses.replace(CFG, capacity_factor=capacity_factor)
    moe = MoE(cfg)
    params = moe.init(jax.random.key(1))
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(4, 8, 32)), jnp.float32
    )
    y_ref, logits_ref, idx_ref = jax.jit(moe)(params, x)

    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, expert_model_parallel_size=2
    )
    mesh = parallel_state.get_parallel_state().mesh
    sharded = shard_pytree(params, moe.specs(), mesh)
    y, logits, idx = jax.jit(moe)(sharded, x)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(idx_ref))
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(logits_ref), atol=1e-5
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)


def test_mixtral_model_trains():
    """Tiny Mixtral: loss finite, grads finite, aux loss contributes."""
    cfg = MIXTRAL_CONFIGS["tiny-moe"]
    model = MixtralForCausalLM(cfg)
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(
        np.random.default_rng(6).integers(0, cfg.vocab_size, (2, 16)), jnp.int32
    )
    loss, grads = jax.jit(jax.value_and_grad(model.loss))(params, ids, ids)
    assert bool(jnp.isfinite(loss))
    for g in jax.tree.leaves(grads):
        assert bool(jnp.all(jnp.isfinite(g)))
    no_aux = dataclasses.replace(cfg, router_aux_loss_coef=0.0)
    loss0 = jax.jit(MixtralForCausalLM(no_aux).loss)(params, ids, ids)
    assert float(loss) != float(loss0)  # aux loss is actually wired in


def test_mixtral_tp_ep_parity():
    """Mixtral under tp=2 × ep=2 × dp=2 == single-device loss (ample
    capacity so EP per-shard dropping can't diverge from the global path)."""
    cfg = dataclasses.replace(MIXTRAL_CONFIGS["tiny-moe"], capacity_factor=8.0)
    model = MixtralForCausalLM(cfg)
    params = model.init(jax.random.key(2))
    ids = jnp.asarray(
        np.random.default_rng(7).integers(0, cfg.vocab_size, (4, 16)), jnp.int32
    )
    ref = jax.jit(model.loss)(params, ids, ids)
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, expert_model_parallel_size=2
    )
    mesh = parallel_state.get_parallel_state().mesh
    sharded = shard_pytree(params, model.specs(), mesh)
    out = jax.jit(model.loss)(sharded, ids, ids)
    assert abs(float(out) - float(ref)) < 1e-4


def test_ep_aware_zero1_specs():
    """Expert params' optimizer state shards over ("dp",) only (expert-DP),
    dense params over ("dp","ep") — reference NeuronEPZero1Optimizer split
    (zero_redundancy_optimizer.py:158)."""
    from neuronx_distributed_llama3_2_tpu.trainer.config import OptimizerConfig
    from neuronx_distributed_llama3_2_tpu.trainer.optimizer import (
        optimizer_state_specs,
    )
    from neuronx_distributed_llama3_2_tpu.parallel.state import DP_AXIS, EP_AXIS

    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, expert_model_parallel_size=2
    )
    cfg = MIXTRAL_CONFIGS["tiny-moe"]
    model = MixtralForCausalLM(cfg)
    params = jax.eval_shape(model.init, jax.random.key(0))
    specs = optimizer_state_specs(
        model.specs(), params, OptimizerConfig(zero_one_enabled=True)
    )
    expert_spec = specs.mu["layers"]["moe"]["experts"]["gate_up"]
    flat = [
        p for part in expert_spec for p in
        (part if isinstance(part, tuple) else (part,))
    ]
    assert DP_AXIS in flat
    assert EP_AXIS in flat  # ep from the param sharding itself
    # the dp-sharding added by zero-1 must NOT pair dp with ep for experts
    assert (DP_AXIS, EP_AXIS) not in expert_spec and not any(
        isinstance(p, tuple) and set(p) == {DP_AXIS, EP_AXIS}
        for p in expert_spec
    )
    dense_spec = specs.mu["layers"]["attn"]["qkv"]["q_kernel"]
    assert any(
        isinstance(p, tuple) and set(p) == {DP_AXIS, EP_AXIS}
        for p in dense_spec
    )


def test_sinkhorn_mixtral_trains_end_to_end():
    """routing='sinkhorn' through the full model + trainer (the reference
    exercises RouterSinkhorn in its MoE golden tests; here: loss decreases
    and gradients reach the router)."""
    import dataclasses

    from neuronx_distributed_llama3_2_tpu.models.mixtral import (
        MIXTRAL_CONFIGS,
        MixtralForCausalLM,
    )
    from neuronx_distributed_llama3_2_tpu.trainer import (
        OptimizerConfig,
        TrainingConfig,
        initialize_parallel_model,
        make_train_step,
    )

    parallel_state.destroy_model_parallel()
    cfg = dataclasses.replace(MIXTRAL_CONFIGS["tiny-moe"], routing="sinkhorn")
    tc = TrainingConfig(
        optimizer=OptimizerConfig(
            zero_one_enabled=False, warmup_steps=1, learning_rate=5e-3
        )
    )
    tc.initialize(devices=jax.devices()[:1])
    try:
        model = MixtralForCausalLM(cfg)
        state, _ = initialize_parallel_model(model, tc)
        step = make_train_step(model, tc)
        ids = jnp.asarray(
            np.random.default_rng(8).integers(0, cfg.vocab_size, (4, 16)),
            jnp.int32,
        )
        losses = []
        for _ in range(6):
            state, m = step(state, {"input_ids": ids, "labels": ids})
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        # router moved (sinkhorn affinities are differentiable through the
        # selected gates)
        fresh = model.init(jax.random.key(tc.seed))
        drift = float(
            jnp.sum(
                jnp.abs(
                    state.params["layers"]["moe"]["router"]["kernel"]
                    - fresh["layers"]["moe"]["router"]["kernel"]
                )
            )
        )
        assert drift > 0
    finally:
        parallel_state.destroy_model_parallel()
