"""Cancellation parity: a client cancel never perturbs survivors.

The contract (serving/engine.py ``cancel`` docstring): cancellation
routes through the existing failure domain — drain the in-flight
lookahead, then ``_fail_request`` (blocks released, lane freed, FINISH
emitted, terminal timing stamped). Survivors' resident state is
untouched, so their token streams must be **identical** to an
uncancelled run of the same workload (greedy recompute determinism, the
same exactness the preemption and fault-tolerance suites pin).

Matrix: the victim is cancelled while queued, mid-chunked-prefill,
mid-decode, and mid-verify (speculative engine, between verify steps).
Every leg tears down with the invariant auditor, the block-pool leak
check, and the GC010 action-trace automaton clean.
"""

import jax
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.analysis.graftcheck import audit_programs
from neuronx_distributed_llama3_2_tpu.analysis.graftsched import (
    check_action_trace,
)
from neuronx_distributed_llama3_2_tpu.inference import (
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.serving import (
    NGramDrafter,
    PagedConfig,
    PagedServingEngine,
    audit_engine,
)

from tests.test_paged_serving import _prompts
from tests.test_speculative_serving import _rep_prompts

TINY = LLAMA_CONFIGS["tiny"]


@pytest.fixture(scope="module")
def params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


def _paged(params, gen, paged_cfg, drafter=None, max_batch=4):
    eng = InferenceEngine(
        TINY, params, max_batch=max_batch, max_seq_len=64,
        buckets=[8, 16, 32],
    )
    return PagedServingEngine(eng, gen, paged_cfg, drafter=drafter)


def _teardown_clean(paged):
    assert paged._pending is None
    assert paged.allocator.active_blocks == 0
    assert paged.allocator.leak_check() == []
    assert audit_engine(paged) == []
    assert audit_programs(paged) == []
    assert check_action_trace(paged) == []


def _run_with_cancel(make_engine, prompts, victim, should_cancel):
    """Submit everything, step until ``should_cancel(info)`` holds for the
    victim, cancel between steps, run to completion. Returns (engine,
    victim tokens at cancel)."""
    paged = make_engine()
    for p in prompts:
        paged.submit(p)
    cancelled_at = None
    alive = True
    while alive:
        alive = paged.step()
        info = paged.request_info(victim)
        if cancelled_at is None and not info["done"] and should_cancel(info):
            assert paged.cancel(victim) is True
            cancelled_at = list(paged.request_tokens(victim))
    assert cancelled_at is not None, "cancel predicate never fired"
    return paged, cancelled_at


def _check_parity(paged, baseline, victim, cancelled_at):
    """Survivors token-identical to the uncancelled run; the victim is a
    terminal failed record whose stream froze at the cancel point."""
    for rid, toks in baseline.items():
        if rid == victim:
            continue
        assert paged.request_tokens(rid) == toks, f"survivor {rid} diverged"
        assert paged.request_info(rid)["status"] == "finished"
    info = paged.request_info(victim)
    assert info["status"] == "failed"
    assert info["error"] == "cancelled by client"
    assert paged.request_tokens(victim) == cancelled_at
    assert paged.metrics.cancelled_requests == 1
    assert paged.metrics.failed_requests == 1
    assert paged.metrics.requests_by_class["batch"]["failed"] == 1
    _teardown_clean(paged)


def test_cancel_while_queued(params):
    """Cancel before admission: the victim never touches a lane or a
    block; the others run exactly as if it were never submitted."""
    gen = GenerationConfig(max_new_tokens=6)
    cfg = dict(block_size=8, num_blocks=64, async_loop=True)
    prompts = _prompts(np.random.default_rng(21), (5, 9, 12, 7, 6))
    victim = 4  # max_batch=4: rid 4 waits in the queue behind the wave

    solo = _paged(params, gen, PagedConfig(**cfg))
    for p in prompts:
        solo.submit(p)
    baseline = solo.run_to_completion()

    paged = _paged(params, gen, PagedConfig(**cfg))
    for p in prompts:
        paged.submit(p)
    assert paged.cancel(victim) is True  # still queued, pre-step
    assert paged.metrics.queued_requests == len(prompts) - 1
    paged.run_to_completion()
    _check_parity(paged, baseline, victim, cancelled_at=[])
    assert paged.request_info(victim)["generated_tokens"] == 0


# shared by the mid-prefill and mid-decode legs (and their single
# uncancelled baseline run): the victim gets the longest prompt so its
# chunk walk spans steps
_CHUNK_GEN = GenerationConfig(max_new_tokens=8)
_CHUNK_CFG = dict(
    block_size=8, num_blocks=64, prefill_chunk_tokens=6, async_loop=True,
)
_CHUNK_PROMPTS = _prompts(np.random.default_rng(23), (5, 26, 9, 7))


@pytest.fixture(scope="module")
def chunk_baseline(params):
    solo = _paged(params, _CHUNK_GEN, PagedConfig(**_CHUNK_CFG))
    for p in _CHUNK_PROMPTS:
        solo.submit(p)
    return solo.run_to_completion()


@pytest.mark.parametrize(
    "when",
    ["mid_prefill", "mid_decode"],
)
def test_cancel_mid_prefill_and_mid_decode(params, chunk_baseline, when):
    """Cancel during the victim's chunk walk (prefilling, no tokens yet)
    and mid-decode (some tokens committed): survivors byte-identical,
    victim's stream frozen at the cancel point."""
    gen, cfg, prompts = _CHUNK_GEN, _CHUNK_CFG, _CHUNK_PROMPTS
    victim = 1
    baseline = chunk_baseline

    if when == "mid_prefill":
        pred = lambda info: info["prefilling"]  # noqa: E731
    else:
        pred = lambda info: info["generated_tokens"] >= 2  # noqa: E731
    paged, cancelled_at = _run_with_cancel(
        lambda: _paged(params, gen, PagedConfig(**cfg)),
        prompts, victim, pred,
    )
    if when == "mid_prefill":
        assert cancelled_at == []  # no token ever committed
    else:
        assert 2 <= len(cancelled_at) < len(baseline[victim])
        assert cancelled_at == baseline[victim][: len(cancelled_at)]
    _check_parity(paged, baseline, victim, cancelled_at)


@pytest.mark.parametrize(
    "when",
    ["mid_prefill", "mid_decode"],
)
def test_cancel_mid_fused_step(params, chunk_baseline, when):
    """fused_step legs of the chunk matrix: the victim's chunk walk rides
    the one-dispatch pmixed grid, the cancel lands between fused
    dispatches, and the survivors must stay byte-identical to the
    UNFUSED uncancelled baseline — cancellation parity and fused-step
    token parity pinned by the same assertion."""
    gen, prompts = _CHUNK_GEN, _CHUNK_PROMPTS
    cfg = dict(_CHUNK_CFG, fused_step=True)
    victim = 1
    baseline = chunk_baseline

    if when == "mid_prefill":
        pred = lambda info: info["prefilling"]  # noqa: E731
    else:
        pred = lambda info: info["generated_tokens"] >= 2  # noqa: E731
    paged, cancelled_at = _run_with_cancel(
        lambda: _paged(params, gen, PagedConfig(**cfg)),
        prompts, victim, pred,
    )
    assert paged.metrics.mixed_dispatches > 0
    if when == "mid_prefill":
        assert cancelled_at == []  # no token ever committed
    else:
        assert 2 <= len(cancelled_at) < len(baseline[victim])
        assert cancelled_at == baseline[victim][: len(cancelled_at)]
    _check_parity(paged, baseline, victim, cancelled_at)


def test_cancel_mid_verify_speculative(params):
    """Speculative engine: cancel between verify steps while the victim
    has accepted drafted tokens. The drain-then-fail path must unwind the
    in-flight lookahead without touching the survivors' accept streams."""
    gen = GenerationConfig(max_new_tokens=12)
    cfg = dict(
        block_size=8, num_blocks=64, async_loop=True,
        spec_draft_tokens=3,
    )
    drafter = NGramDrafter()
    prompts = _rep_prompts(np.random.default_rng(17), (12, 15, 9))
    victim = 1

    solo = _paged(params, gen, PagedConfig(**cfg), drafter=NGramDrafter())
    for p in prompts:
        solo.submit(p)
    baseline = solo.run_to_completion()

    def pred(info):
        # at least one verify step has run and the victim holds tokens —
        # the cancel lands between verify dispatches
        return (
            paged_ref[0].metrics.verify_steps >= 2
            and info["generated_tokens"] >= 1
        )

    paged_ref = []

    def make():
        eng = _paged(params, gen, PagedConfig(**cfg), drafter=drafter)
        paged_ref.append(eng)
        return eng

    paged, cancelled_at = _run_with_cancel(make, prompts, victim, pred)
    assert paged.metrics.verify_steps >= 2
    assert cancelled_at == baseline[victim][: len(cancelled_at)]
    assert len(cancelled_at) < len(baseline[victim])
    _check_parity(paged, baseline, victim, cancelled_at)
