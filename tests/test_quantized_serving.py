"""Quantized paged KV pool (``PagedConfig.kv_cache_dtype``): parity matrix,
COW-with-scales, fp-path regression, capacity accounting, spec drift canary.

The exactness property under test is stronger than "quantized is close to
fp": because the per-(row, kv-head) scales are append-local (quantize on
write, dequantize identically on every read path), EVERY quantized engine
configuration — gather or kernel, sync or async, chunked or whole prefill,
tp=1 or tp=2 — must produce token-IDENTICAL greedy outputs. Only the
quantized-vs-fp comparison gets a tolerance band (the int8 round-trip error
itself). The fp path must be structurally untouched: scales default to
``None`` and the cache flattens to the same ``(k, v)`` pair as before.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.analysis.graftcheck import audit_programs
from neuronx_distributed_llama3_2_tpu.inference import (
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.inference.model import LlamaDecode
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.parallel.state import (
    initialize_model_parallel,
    kv_head_shard_size,
)
from neuronx_distributed_llama3_2_tpu.quantization import (
    KV_CACHE_DTYPES,
    KV_SCALE_DTYPE,
    kv_dequantize,
    kv_quantize,
    kv_scale_itemsize,
)
from neuronx_distributed_llama3_2_tpu.serving import (
    PagedConfig,
    PagedServingEngine,
    audit_engine,
)
from neuronx_distributed_llama3_2_tpu.serving.block_allocator import (
    kv_pool_bytes_per_rank,
)

from tests.test_async_serving import _paged, _run
from tests.test_paged_serving import _prompts

TINY = LLAMA_CONFIGS["tiny"]
TINY_KERNEL = dataclasses.replace(TINY, use_paged_kernel=True)


@pytest.fixture(scope="module")
def params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


def _qcfg(**kw):
    kw.setdefault("kv_cache_dtype", "int8")
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 64)
    return PagedConfig(**kw)


@pytest.fixture(scope="module")
def int8_baseline(params):
    """Reference cell of the parity matrix: int8, gather, sync, whole."""
    gen = GenerationConfig(max_new_tokens=8)
    prompts = _prompts(np.random.default_rng(7), (5, 12, 20, 9))
    out = _run(_paged(params, gen, _qcfg()), prompts)
    return gen, prompts, out


# -- scale-math units ------------------------------------------------------


def test_kv_quantize_roundtrip_int8():
    x = jax.random.normal(jax.random.key(1), (4, 8, 3, 16), jnp.float32) * 5.0
    q, s = kv_quantize(x, jnp.int8)
    assert q.dtype == jnp.int8 and q.shape == x.shape
    assert s.dtype == KV_SCALE_DTYPE and s.shape == x.shape[:-1]
    y = kv_dequantize(q, s, jnp.float32)
    # symmetric absmax: per-element error bounded by half a quantization
    # step, i.e. scale/2 per (row, head)
    err = jnp.max(jnp.abs(y - x) / jnp.maximum(s.astype(jnp.float32)[..., None], 1e-6))
    assert float(err) <= 0.5 + 1e-3
    # write/read stability: re-quantizing the dequantized values must be a
    # fixed point (the engine round-trips fresh K/V through the pool)
    q2, s2 = kv_quantize(y, jnp.int8)
    assert jnp.array_equal(q, q2) and jnp.array_equal(s, s2)


def test_kv_quantize_zero_rows_and_fp8():
    z = jnp.zeros((2, 4, 2, 8), jnp.float32)
    q, s = kv_quantize(z, jnp.int8)
    assert jnp.array_equal(kv_dequantize(q, s, jnp.float32), z)
    for name in ("fp8_e4m3", "fp8_e5m2"):
        dt = KV_CACHE_DTYPES[name]
        x = jax.random.normal(jax.random.key(2), (2, 4, 2, 8), jnp.float32)
        q, s = kv_quantize(x, dt)
        y = kv_dequantize(q, s, jnp.float32)
        assert q.dtype == dt and bool(jnp.all(jnp.isfinite(y)))
        assert float(jnp.max(jnp.abs(y - x))) < 0.2 * float(jnp.max(jnp.abs(x)))


def test_kv_cache_dtype_validation(params):
    assert set(KV_CACHE_DTYPES) == {"bf16", "int8", "fp8_e4m3", "fp8_e5m2"}
    assert kv_scale_itemsize("bf16") == 0
    assert kv_scale_itemsize("int8") == kv_scale_itemsize("fp8_e4m3") == 2
    gen = GenerationConfig(max_new_tokens=4)
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        _paged(params, gen, _qcfg(kv_cache_dtype="int4"))
    with pytest.raises(ValueError, match="cache_dtype"):
        _paged(params, gen, _qcfg(cache_dtype=jnp.bfloat16))


# -- fp-path regression ----------------------------------------------------


def test_fp_default_cache_has_no_scale_arrays(params):
    """Structural bitwise guarantee: the default (bf16) pool is the exact
    pre-quantization pytree — two payload leaves, no scale fields — so fp
    traces, donation, and sharding specs are untouched."""
    m = LlamaDecode(TINY)
    cache = m.init_paged_cache(16, 8)
    assert cache.k_scale is None and cache.v_scale is None
    assert not cache.quantized
    assert len(jax.tree.leaves(cache)) == 2
    qc = m.init_paged_cache(16, 8, kv_cache_dtype="int8")
    assert qc.quantized and qc.k.dtype == jnp.int8
    assert qc.k_scale.dtype == KV_SCALE_DTYPE
    assert qc.k_scale.shape == qc.k.shape[:-1]
    assert len(jax.tree.leaves(qc)) == 4
    with pytest.raises(ValueError):
        m.init_paged_cache(16, 8, dtype=jnp.bfloat16, kv_cache_dtype="int8")


def test_fp_engine_metrics_and_pool_bytes_unchanged(params):
    gen = GenerationConfig(max_new_tokens=4)
    paged = _paged(params, gen, PagedConfig(block_size=8, num_blocks=16))
    snap = paged.metrics.snapshot(paged.allocator)
    assert snap["kv_dtype"] == "bf16"
    assert snap["pool_bytes_per_rank"] == kv_pool_bytes_per_rank(
        num_layers=TINY.num_layers, num_blocks=16, block_size=8,
        num_kv_heads=TINY.num_kv_heads, head_dim=TINY.head_dim,
        dtype_bytes=4,  # tiny runs fp32 on CPU
    )


def test_dense_path_rejects_quantized_cache(params):
    m = LlamaDecode(TINY)
    qc = m.init_paged_cache(16, 8, kv_cache_dtype="int8")
    ids = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="quantized"):
        m.forward(params, qc, ids, jnp.zeros((1,), jnp.int32))


# -- engine parity matrix --------------------------------------------------


@pytest.mark.parametrize("model_cfg", [TINY, TINY_KERNEL], ids=["gather", "kernel"])
@pytest.mark.parametrize("async_loop", [False, True], ids=["sync", "async"])
@pytest.mark.parametrize("chunk", [None, 6], ids=["whole", "chunked"])
def test_quantized_parity_matrix(params, int8_baseline, model_cfg, async_loop, chunk):
    """Every int8 cell is token-identical to the reference cell: the
    append-local scales make quantized values independent of prefill
    chunking, loop mode, and kernel-vs-gather eligibility."""
    gen, prompts, want = int8_baseline
    paged = _paged(
        params, gen,
        _qcfg(async_loop=async_loop, prefill_chunk_tokens=chunk),
        model_cfg=model_cfg,
    )
    assert _run(paged, prompts) == want
    assert paged.metrics.kv_dtype == "int8"
    assert paged.metrics.snapshot()["kv_dtype"] == "int8"


@pytest.mark.parametrize(
    "kv_dtype",
    # tier-1 time budget: one fp8 flavour in the default tier, the other slow
    ["fp8_e4m3", pytest.param("fp8_e5m2", marks=pytest.mark.slow)],
)
def test_fp8_gather_matches_kernel(params, kv_dtype):
    gen = GenerationConfig(max_new_tokens=6)
    prompts = _prompts(np.random.default_rng(11), (5, 12, 9))
    got_g = _run(_paged(params, gen, _qcfg(kv_cache_dtype=kv_dtype)), prompts)
    got_k = _run(
        _paged(params, gen, _qcfg(kv_cache_dtype=kv_dtype), model_cfg=TINY_KERNEL),
        prompts,
    )
    assert got_g == got_k


def test_int8_logits_within_tolerance_of_fp(params):
    """The only non-exact comparison: quantized vs fp logits after a paged
    prefill + one decode step sit inside the int8 round-trip band
    (measured ~0.25% relative on tiny; asserted at 5%)."""
    m = LlamaDecode(TINY)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, TINY.vocab_size, size=(2, 16)), jnp.int32)
    tables = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    pos0 = jnp.zeros((2,), jnp.int32)

    def one(kv_dtype):
        cache = m.init_paged_cache(16, 8, kv_cache_dtype=kv_dtype)
        lg, cache = m.forward(
            params, cache, ids, pos0,
            block_tables=tables, context_encode=kv_dtype is None,
        )
        tok = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)
        lg2, _, _ = m.decode_step(
            params, cache, tok, jnp.full((2,), 16, jnp.int32), tables,
            kv_limit=32,
        )
        return lg2

    fp, q = one(None), one("int8")
    rel = jnp.max(jnp.abs(fp - q)) / jnp.max(jnp.abs(fp))
    assert float(rel) < 0.05


# -- low-precision MXU decode dot (PagedConfig.quant_mxu) -------------------


@pytest.mark.parametrize("async_loop", [False, True], ids=["sync", "async"])
def test_quant_mxu_parity_cells(params, int8_baseline, async_loop):
    """quant_mxu rows of the parity matrix: the int8-accumulate q·k dot
    (scales applied post-dot) stays token-identical to the reference int8
    cell on tiny — measured zero greedy drift; the formal gate is the 5%
    logits band of test_quant_mxu_logits_within_band_of_fp."""
    gen, prompts, want = int8_baseline
    paged = _paged(
        params, gen,
        _qcfg(quant_mxu=True, async_loop=async_loop),
        model_cfg=TINY_KERNEL,
    )
    assert _run(paged, prompts) == want
    assert paged.model.config.quant_mxu


def test_quant_mxu_logits_within_band_of_fp(params):
    """The acceptance band from the quant parity matrix: decode logits
    through the MXU-native int8 dot sit inside 5% of the FP cache path
    (the widened int8 path already sits inside the same band above)."""
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, TINY.vocab_size, size=(2, 16)), jnp.int32)
    tables = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    pos0 = jnp.zeros((2,), jnp.int32)

    def one(kv_dtype, quant_mxu=False):
        m = LlamaDecode(
            dataclasses.replace(TINY_KERNEL, quant_mxu=quant_mxu)
        )
        cache = m.init_paged_cache(16, 8, kv_cache_dtype=kv_dtype)
        lg, cache = m.forward(
            params, cache, ids, pos0,
            block_tables=tables, context_encode=kv_dtype is None,
        )
        tok = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)
        lg2, _, _ = m.decode_step(
            params, cache, tok, jnp.full((2,), 16, jnp.int32), tables,
            kv_limit=32,
        )
        return lg2

    fp, mxu = one(None), one("int8", quant_mxu=True)
    rel = jnp.max(jnp.abs(fp - mxu)) / jnp.max(jnp.abs(fp))
    assert float(rel) < 0.05


# -- COW with scales -------------------------------------------------------


def test_copy_block_fn_copies_scale_rows(params):
    gen = GenerationConfig(max_new_tokens=4)
    paged = _paged(params, gen, _qcfg(num_blocks=8))
    c = paged.cache
    c = type(c)(
        k=c.k.at[:, 2].set(7), v=c.v.at[:, 2].set(-7),
        k_scale=c.k_scale.at[:, 2].set(3.0),
        v_scale=c.v_scale.at[:, 2].set(5.0),
    )
    out = paged._copy_block_fn(
        c, jnp.asarray(2, jnp.int32), jnp.asarray(5, jnp.int32)
    )
    assert bool(jnp.all(out.k[:, 5] == 7)) and bool(jnp.all(out.v[:, 5] == -7))
    assert bool(jnp.all(out.k_scale[:, 5] == 3.0))
    assert bool(jnp.all(out.v_scale[:, 5] == 5.0))


def test_cow_prefix_share_stays_exact(params):
    """Prefix-cached int8 engine == uncached int8 engine: COW copies the
    scale tile with the payload tile, so a shared partial block diverges
    safely after the copy."""
    gen = GenerationConfig(max_new_tokens=6)
    rng = np.random.default_rng(5)
    shared = rng.integers(0, TINY.vocab_size, size=(20,)).tolist()
    prompts = [
        shared + rng.integers(0, TINY.vocab_size, size=(4,)).tolist()
        for _ in range(4)
    ]
    cached = _paged(params, gen, _qcfg(), model_cfg=TINY_KERNEL)
    out = _run(cached, prompts)
    assert cached.metrics.cached_tokens > 0
    assert cached.allocator.cow_copies >= 1
    uncached = _paged(
        params, gen, _qcfg(enable_prefix_caching=False), model_cfg=TINY_KERNEL
    )
    assert _run(uncached, prompts) == out


# -- speculative decoding drift canary -------------------------------------


@pytest.mark.slow  # tier-1 time budget; statistical canary, not a parity gate
def test_spec_accept_rate_drift_canary(params):
    """Soak canary: the n-gram drafter's accept rate under int8 must track
    the fp rate — quantization error that flipped verify argmaxes would
    show up here as drift."""
    gen = GenerationConfig(max_new_tokens=16)
    rng = np.random.default_rng(9)
    pattern = rng.integers(0, TINY.vocab_size, size=(6,)).tolist()
    prompts = [pattern * 5, pattern * 4 + pattern[:3]]

    def accept(kv_dtype):
        paged = _paged(
            params, gen,
            _qcfg(kv_cache_dtype=kv_dtype, spec_draft_tokens=3),
            model_cfg=TINY_KERNEL,
        )
        out = _run(paged, prompts)
        assert paged.metrics.draft_tokens > 0
        return paged.metrics.accept_rate(), out

    fp_rate, _ = accept("bf16")
    q_rate, q_out = accept("int8")
    assert abs(fp_rate - q_rate) <= 0.15
    # and speculation does not change the int8 tokens themselves
    plain = _run(_paged(params, gen, _qcfg(), model_cfg=TINY_KERNEL), prompts)
    assert q_out == plain


# -- residency (zero-upload steady state) ----------------------------------


def test_quantized_steady_state_is_fully_resident(params):
    """The PR-4 acceptance check holds under int8: steady-state async steps
    do zero host→device uploads — quantize-on-write lives inside the same
    donated decode program, so no extra transfers appear."""
    gen = GenerationConfig(max_new_tokens=24)
    paged = _paged(
        params, gen,
        PagedConfig(
            block_size=32, num_blocks=8, async_loop=True,
            kv_cache_dtype="int8",
        ),
    )
    paged.submit(_prompts(np.random.default_rng(0), (4,))[0])
    paged.step()
    paged.step()
    m = paged.metrics
    for _ in range(12):
        before = (m.h2d_uploads, m.lane_syncs, m.table_deltas)
        assert paged.step()
        assert (m.h2d_uploads, m.lane_syncs, m.table_deltas) == before
        assert paged._last_readback_lag == 1
    paged.run_to_completion()
    # quantized teardown: pool drained, scale arrays still matching dtype
    assert paged.allocator.leak_check() == []
    assert audit_engine(paged) == []
    assert audit_programs(paged) == []


# -- tensor parallel -------------------------------------------------------


def test_quantized_tp2_matches_tp1_and_pool_bytes(params, int8_baseline):
    """tp=2 int8 kernel engine is token-identical to tp=1, the scale
    arrays shard the same kv-head split, and per-rank pool bytes (payload
    + scales) are exactly half the logical pool."""
    gen, prompts, want = int8_baseline
    initialize_model_parallel(
        tensor_model_parallel_size=2, devices=jax.devices()[:2]
    )
    paged = _paged(params, gen, _qcfg(), model_cfg=TINY_KERNEL)
    assert _run(paged, prompts) == want
    m = paged.metrics
    assert m.tp_size == 2 and m.kv_dtype == "int8"
    assert m.pool_bytes_total == 2 * m.pool_bytes_per_rank
    heads_rank = kv_head_shard_size(TINY.num_kv_heads)
    assert heads_rank == TINY.num_kv_heads // 2
    assert m.pool_bytes_per_rank == kv_pool_bytes_per_rank(
        num_layers=TINY.num_layers, num_blocks=64, block_size=8,
        num_kv_heads=TINY.num_kv_heads, head_dim=TINY.head_dim,
        dtype_bytes=1, tp_size=2, scale_bytes=kv_scale_itemsize("int8"),
    )


# -- capacity accounting ---------------------------------------------------


def test_int8_capacity_ratio_at_llama_geometry():
    """Acceptance number: at llama-class head_dim=64 and fixed per-chip
    pool bytes, int8 (+fp16 scales) fits ≥1.9× the bf16 resident lanes."""
    geom = dict(
        num_layers=32, num_blocks=1024, block_size=16,
        num_kv_heads=8, head_dim=64,
    )
    bf16 = kv_pool_bytes_per_rank(dtype_bytes=2, **geom)
    int8 = kv_pool_bytes_per_rank(
        dtype_bytes=1, scale_bytes=kv_scale_itemsize("int8"), **geom
    )
    ratio = bf16 / int8
    assert ratio >= 1.9
    # equivalently: at a fixed byte budget, the block count (→ resident
    # lanes or kv_limit) scales by the same factor
    budget = bf16
    blocks_bf16 = budget // (bf16 // geom["num_blocks"])
    blocks_int8 = budget // (int8 // geom["num_blocks"])
    assert blocks_int8 >= 1.9 * blocks_bf16
