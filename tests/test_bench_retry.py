"""bench.py retry-orchestrator tests.

Round-2 lesson (VERDICT): the relay outage hung backend init ~25 min
in-process, so the driver saw rc=124 with nothing to parse. The orchestrator
now runs each attempt in a timeout-bounded subprocess and, on exhaustion,
prints ONE parseable JSON failure line and exits fast.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


GOOD_LINE = (
    json.dumps(
        {
            "metric": "llama3.2-1b_train_tokens_per_sec_per_chip",
            "value": 12345.0,
            "unit": "tokens/s",
            "vs_baseline": 1.07,
        }
    )
    + "\n"
)


def test_transient_error_retries_then_forwards_stdout(capsys):
    bench = _load_bench()
    calls = {"n": 0}

    def fake_launch(timeout_s):
        calls["n"] += 1
        if calls["n"] == 1:
            return "error", "", "UNAVAILABLE: TPU backend setup/compile error"
        return "ok", GOOD_LINE, ""

    bench.main_with_retries(
        attempts=3, backoff_s=0.0, deadline_s=60.0, attempt_timeout_s=10.0,
        launch=fake_launch, probe=lambda: "ok",
    )
    assert calls["n"] == 2
    out = capsys.readouterr().out
    rec = json.loads(out.strip())
    assert rec["vs_baseline"] == 1.07
    # the up-front relay preflight stamps its verdict into the headline
    assert rec["preflight"] == "ok"


def test_hung_attempt_times_out_and_retries(capsys):
    bench = _load_bench()
    calls = {"n": 0}

    def fake_launch(timeout_s):
        calls["n"] += 1
        assert timeout_s <= 10.0  # per-attempt bound is enforced
        if calls["n"] == 1:
            return "timeout", "", ""  # init hang, killed by the bound
        return "ok", GOOD_LINE, ""

    bench.main_with_retries(
        attempts=3, backoff_s=0.0, deadline_s=60.0, attempt_timeout_s=10.0,
        launch=fake_launch, probe=lambda: "ok",
    )
    assert calls["n"] == 2


def test_non_transient_fails_immediately_with_record(capsys):
    """A non-transient failure must still produce a machine-readable JSON
    record (ADVICE r3) carrying the probe classification."""
    bench = _load_bench()
    calls = {"n": 0}

    def fake_launch(timeout_s):
        calls["n"] += 1
        return "error", "", "RuntimeError: non-finite loss nan on the bench step"

    with pytest.raises(SystemExit):
        bench.main_with_retries(
            attempts=3, backoff_s=0.0, deadline_s=60.0, attempt_timeout_s=10.0,
            launch=fake_launch, probe=lambda: "ok",
        )
    assert calls["n"] == 1
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "non-transient" in rec["error"]
    assert rec["probe"] == "ok"  # backend healthy => this is a regression


def test_exhausted_retries_emit_parseable_failure_record(capsys):
    bench = _load_bench()
    calls = {"n": 0}

    def fake_launch(timeout_s):
        calls["n"] += 1
        return "error", "", "UNAVAILABLE: still down"

    with pytest.raises(SystemExit):
        bench.main_with_retries(
            attempts=3, backoff_s=0.0, deadline_s=60.0, attempt_timeout_s=10.0,
            launch=fake_launch, probe=lambda: "backend_init_timeout",
        )
    assert calls["n"] == 3
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == bench.METRIC_NAME
    assert rec["value"] is None and rec["vs_baseline"] is None
    assert "backend unavailable" in rec["error"]
    assert rec["probe"] == "backend_init_timeout"  # outage, not regression


def test_deadline_caps_total_wall_clock(capsys):
    """Even with many attempts configured, the deadline bounds the loop so
    the driver's own timeout is never consumed by our retries."""
    bench = _load_bench()
    calls = {"n": 0}

    def fake_launch(timeout_s):
        calls["n"] += 1
        # each "attempt" pretends to burn the whole budget
        return "timeout", "", ""

    import time as _time

    t0 = _time.monotonic()
    with pytest.raises(SystemExit):
        bench.main_with_retries(
            attempts=100, backoff_s=0.5, deadline_s=1.0, attempt_timeout_s=0.01,
            launch=fake_launch, probe=lambda: "backend_init_timeout",
        )
    elapsed = _time.monotonic() - t0
    assert elapsed < 10.0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"]


def test_env_overrides(monkeypatch):
    bench = _load_bench()
    monkeypatch.setenv("BENCH_RETRY_ATTEMPTS", "1")
    monkeypatch.setenv("BENCH_RETRY_BACKOFF_S", "0")
    monkeypatch.setenv("BENCH_DEADLINE_S", "5")
    monkeypatch.setenv("BENCH_ATTEMPT_TIMEOUT_S", "2")
    seen = {}

    def fake_launch(timeout_s):
        seen["timeout"] = timeout_s
        return "ok", GOOD_LINE, ""

    bench.main_with_retries(launch=fake_launch, probe=lambda: "ok")
    assert seen["timeout"] == 2.0


def test_preflight_verdict_stamped_into_headline(capsys):
    """The preflight probe runs BEFORE any attempt and its verdict lands in
    the headline record as provenance — a degraded-relay verdict must ride
    a healthy-looking number, and surrounding chatter must survive."""
    bench = _load_bench()
    probes = {"n": 0}

    def probe():
        probes["n"] += 1
        return "backend_init_timeout"

    bench.main_with_retries(
        attempts=1, backoff_s=0.0, deadline_s=60.0, attempt_timeout_s=10.0,
        launch=lambda t: ("ok", "# chatter\n" + GOOD_LINE, ""),
        probe=probe,
    )
    assert probes["n"] == 1  # one up-front probe, reused everywhere
    lines = capsys.readouterr().out.strip().splitlines()
    assert lines[0] == "# chatter"
    rec = json.loads(lines[-1])
    assert rec["preflight"] == "backend_init_timeout"
    assert rec["vs_baseline"] == 1.07
