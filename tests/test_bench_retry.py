"""bench.py retry-wrapper tests: transient UNAVAILABLE drops retry (with
parallel state cleared so re-init works); real errors propagate at once."""

import importlib.util
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_transient_retries_then_succeeds(monkeypatch):
    bench = _load_bench()
    from neuronx_distributed_llama3_2_tpu.parallel import state as ps

    calls = {"n": 0, "destroyed": 0}
    orig_destroy = ps.destroy_model_parallel

    def fake_destroy():
        calls["destroyed"] += 1
        orig_destroy()

    monkeypatch.setattr(ps, "destroy_model_parallel", fake_destroy)

    def fake_main():
        calls["n"] += 1
        if calls["n"] == 1:
            # simulate a mid-run drop AFTER the mesh came up
            ps.initialize_model_parallel()
            raise RuntimeError("UNAVAILABLE: TPU backend setup/compile error")

    monkeypatch.setattr(bench, "main", fake_main)
    bench.main_with_retries(attempts=3, backoff_s=0.0)
    assert calls["n"] == 2
    assert calls["destroyed"] >= 1  # state cleared before the retry


def test_non_transient_raises_immediately(monkeypatch):
    bench = _load_bench()
    calls = {"n": 0}

    def fake_main():
        calls["n"] += 1
        raise RuntimeError("non-finite loss nan on the bench step")

    monkeypatch.setattr(bench, "main", fake_main)
    with pytest.raises(RuntimeError, match="non-finite"):
        bench.main_with_retries(attempts=3, backoff_s=0.0)
    assert calls["n"] == 1


def test_exhausted_retries_raise(monkeypatch):
    bench = _load_bench()
    calls = {"n": 0}

    def fake_main():
        calls["n"] += 1
        raise RuntimeError("UNAVAILABLE: still down")

    monkeypatch.setattr(bench, "main", fake_main)
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        bench.main_with_retries(attempts=3, backoff_s=0.0)
    assert calls["n"] == 3
