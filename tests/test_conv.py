"""Channel-parallel conv tests (reference layers.py:1033,1134 — the vision
path TP layers; VERDICT coverage row #10)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state
from neuronx_distributed_llama3_2_tpu.parallel.conv import (
    InputChannelParallelConv2d,
    OutputChannelParallelConv2d,
)
from neuronx_distributed_llama3_2_tpu.parallel.layers import shard_pytree


def _x(b=2, h=8, w=8, c=16, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal((b, h, w, c)), jnp.float32
    )


def _dense(layer, params, x):
    """Un-meshed single-device execution as the oracle."""
    return layer(params, x)


def test_output_parallel_matches_dense_under_tp():
    parallel_state.initialize_model_parallel(tensor_model_parallel_size=4)
    layer = OutputChannelParallelConv2d(
        16, 32, kernel_size=3, padding=1, gather_output=True
    )
    params = layer.init(jax.random.key(0))
    x = _x()
    ref = _dense(layer, params, x)
    sharded = shard_pytree(params, layer.specs())
    out = jax.jit(layer.__call__)(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_conv_pair_column_row_chaining():
    """Output-parallel -> input-parallel composes without a gather between
    (the conv analogue of Column->Row linear, and the reason gather_output
    defaults off)."""
    parallel_state.initialize_model_parallel(tensor_model_parallel_size=4)
    c1 = OutputChannelParallelConv2d(16, 32, kernel_size=3, padding=1)
    c2 = InputChannelParallelConv2d(32, 8, kernel_size=1)
    p1, p2 = c1.init(jax.random.key(1)), c2.init(jax.random.key(2))
    x = _x()
    ref = _dense(c2, p2, _dense(c1, p1, x))
    s1 = shard_pytree(p1, c1.specs())
    s2 = shard_pytree(p2, c2.specs())
    out = jax.jit(lambda a, b, x: c2(b, c1(a, x)))(s1, s2, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # intermediate channel dim is genuinely tp-sharded
    mid = jax.jit(c1.__call__)(s1, x)
    assert mid.sharding.spec[-1] == "tp"


def test_conv_grads_match_dense():
    parallel_state.initialize_model_parallel(tensor_model_parallel_size=2)
    c1 = OutputChannelParallelConv2d(8, 16, kernel_size=3, padding=1)
    c2 = InputChannelParallelConv2d(16, 4, kernel_size=1)
    p1, p2 = c1.init(jax.random.key(3)), c2.init(jax.random.key(4))
    x = _x(b=4, c=8, seed=5)  # batch divisible by dp=4 (8 devices / tp=2)

    def loss(p1, p2, x):
        return jnp.sum(c2(p2, c1(p1, x)) ** 2)

    ref = jax.grad(loss, argnums=(0, 1))(p1, p2, x)
    s1, s2 = shard_pytree(p1, c1.specs()), shard_pytree(p2, c2.specs())
    got = jax.jit(jax.grad(loss, argnums=(0, 1)))(s1, s2, x)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_stride_and_rect_kernel():
    layer = OutputChannelParallelConv2d(
        4, 8, kernel_size=(3, 1), stride=(2, 1), padding=(1, 0),
        gather_output=True,
    )
    params = layer.init(jax.random.key(6))
    out = layer(params, _x(c=4))
    assert out.shape == (2, 4, 8, 8)
