"""Mllama (Llama-3.2 Vision) tests: logits parity vs HF transformers on a
tiny config (the 11B-Vision family named in BASELINE.json; the reference
repo ships no vision modeling code, so HF is the oracle)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.models.mllama import (
    MllamaConfig,
    MllamaForConditionalGeneration,
    MllamaTextConfig,
    MllamaVisionConfig,
    mllama_params_from_hf,
    prepare_cross_attention_mask,
)
from neuronx_distributed_llama3_2_tpu.parallel import state as parallel_state
from neuronx_distributed_llama3_2_tpu.parallel.layers import shard_pytree
from neuronx_distributed_llama3_2_tpu.utils import compat

TINY = MllamaConfig(
    vision=MllamaVisionConfig(
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=3,
        num_global_layers=2,
        attention_heads=2,
        image_size=28,
        patch_size=14,
        max_num_tiles=2,
        max_aspect_ratio_id=3,
        intermediate_layers_indices=(0, 2),
    ),
    text=MllamaTextConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=4,
        num_heads=4,
        num_kv_heads=2,
        cross_attention_layers=(1, 3),
        rope_theta=10000.0,
        max_seq_len=64,
    ),
)


def _hf_tiny():
    import torch
    from transformers import MllamaForConditionalGeneration as HF
    from transformers.models.mllama.configuration_mllama import (
        MllamaConfig as HFConfig,
        MllamaTextConfig as HFText,
        MllamaVisionConfig as HFVision,
    )

    c = TINY
    hf_cfg = HFConfig(
        vision_config=HFVision(
            hidden_size=c.vision.hidden_size,
            intermediate_size=c.vision.intermediate_size,
            num_hidden_layers=c.vision.num_hidden_layers,
            num_global_layers=c.vision.num_global_layers,
            attention_heads=c.vision.attention_heads,
            image_size=c.vision.image_size,
            patch_size=c.vision.patch_size,
            max_num_tiles=c.vision.max_num_tiles,
            intermediate_layers_indices=list(c.vision.intermediate_layers_indices),
            supported_aspect_ratios=[[1, 1], [1, 2], [2, 1]],
            vision_output_dim=c.vision.output_dim,
        ),
        text_config=HFText(
            vocab_size=c.text.vocab_size,
            hidden_size=c.text.hidden_size,
            intermediate_size=c.text.intermediate_size,
            num_hidden_layers=c.text.num_hidden_layers,
            num_attention_heads=c.text.num_heads,
            num_key_value_heads=c.text.num_kv_heads,
            cross_attention_layers=list(c.text.cross_attention_layers),
            rope_theta=c.text.rope_theta,
            rope_scaling={"rope_type": "default"},
            max_position_embeddings=c.text.max_seq_len,
            tie_word_embeddings=False,
            pad_token_id=0,
            bos_token_id=1,
            eos_token_id=2,
        ),
        image_token_index=3,
    )
    torch.manual_seed(0)
    model = HF(hf_cfg).eval()
    return model


def _inputs(seed=0, b=2, s=24):
    rng = np.random.default_rng(seed)
    c = TINY
    pix = rng.standard_normal(
        (b, 1, c.vision.max_num_tiles, 3, c.vision.image_size, c.vision.image_size)
    ).astype(np.float32)
    ids = rng.integers(0, c.text.vocab_size, (b, s)).astype(np.int64)
    ar_ids = np.array([[1], [2]])  # (1,1) and (1,2) aspect ratios
    ar_mask = np.array([[[1, 0]], [[1, 1]]])  # second image uses both tiles
    # text tokens attend image 0's valid tiles from position 4 on
    xmask = np.zeros((b, s, 1, c.vision.max_num_tiles), np.int64)
    xmask[0, 4:, 0, 0] = 1
    xmask[1, 4:, 0, :] = 1
    return pix, ids, ar_ids, ar_mask, xmask


@pytest.fixture(scope="module")
def hf_and_params():
    hf = _hf_tiny()
    params = mllama_params_from_hf(hf.state_dict(), TINY)
    return hf, params


def test_vision_encoder_matches_hf(hf_and_params):
    import torch

    hf, params = hf_and_params
    pix, ids, ar_ids, ar_mask, xmask = _inputs()
    with torch.no_grad():
        ref = hf.model.vision_model(
            torch.tensor(pix), torch.tensor(ar_ids), torch.tensor(ar_mask)
        ).last_hidden_state.numpy()

    from neuronx_distributed_llama3_2_tpu.models.mllama import MllamaVisionModel

    out = jax.jit(MllamaVisionModel(TINY.vision).__call__)(
        params["vision_model"], jnp.asarray(pix), jnp.asarray(ar_ids),
        jnp.asarray(ar_mask),
    )
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=1e-3)


def test_full_model_logits_match_hf(hf_and_params):
    import torch

    hf, params = hf_and_params
    pix, ids, ar_ids, ar_mask, xmask = _inputs()
    with torch.no_grad():
        ref = hf(
            input_ids=torch.tensor(ids),
            pixel_values=torch.tensor(pix),
            aspect_ratio_ids=torch.tensor(ar_ids),
            aspect_ratio_mask=torch.tensor(ar_mask),
            cross_attention_mask=torch.tensor(xmask),
        ).logits.numpy()

    model = MllamaForConditionalGeneration(TINY)
    out = jax.jit(model.__call__)(
        params, jnp.asarray(ids), jnp.asarray(pix), jnp.asarray(ar_ids),
        jnp.asarray(ar_mask), jnp.asarray(xmask),
    )
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-3, rtol=1e-3)


def test_full_row_mask_zeroes_textonly_rows():
    xmask = np.zeros((1, 6, 1, 2), np.int64)
    xmask[0, 3:, 0, 0] = 1
    bias, full_row = prepare_cross_attention_mask(jnp.asarray(xmask), 5)
    assert full_row.shape == (1, 1, 6, 1)
    np.testing.assert_array_equal(
        np.asarray(full_row[0, 0, :, 0]), [0, 0, 0, 1, 1, 1]
    )
    # masked-out rows have all-NEG bias rows before scaling
    assert float(bias[0, 0, 0].max()) == 0.0  # zeroed by full_row multiply


def test_mllama_under_tp(hf_and_params):
    """tp=4 sharded execution matches the unsharded logits."""
    _, params = hf_and_params
    pix, ids, ar_ids, ar_mask, xmask = _inputs()
    model = MllamaForConditionalGeneration(TINY)
    ref = jax.jit(model.__call__)(
        params, jnp.asarray(ids), jnp.asarray(pix), jnp.asarray(ar_ids),
        jnp.asarray(ar_mask), jnp.asarray(xmask),
    )
    parallel_state.initialize_model_parallel(tensor_model_parallel_size=4)
    sharded = shard_pytree(params, model.specs())
    out = jax.jit(model.__call__)(
        sharded, jnp.asarray(ids), jnp.asarray(pix), jnp.asarray(ar_ids),
        jnp.asarray(ar_mask), jnp.asarray(xmask),
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4, rtol=1e-3
    )


def test_mllama_loss_and_grads_finite(hf_and_params):
    _, params = hf_and_params
    pix, ids, ar_ids, ar_mask, xmask = _inputs()
    model = MllamaForConditionalGeneration(TINY)
    loss, grads = jax.jit(
        jax.value_and_grad(
            lambda p: model.loss(
                p, jnp.asarray(ids), jnp.asarray(ids), jnp.asarray(pix),
                jnp.asarray(ar_ids), jnp.asarray(ar_mask), jnp.asarray(xmask),
            )
        )
    )(params)
    assert np.isfinite(float(loss))
    assert all(bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads))
    # cross-attn gates are zero-init: they still receive gradient signal
    from neuronx_distributed_llama3_2_tpu.models.mllama import (
        text_group_pattern,
        text_layer_slice,
    )

    lp, is_cross = text_layer_slice(
        grads["layers"], 1, text_group_pattern(TINY.text)
    )
    assert is_cross
    assert float(jnp.abs(lp["cross_attn_attn_gate"]).max()) > 0


def test_vision_remat_full_matches_none(hf_and_params):
    """vision remat="full" (the 11B memory-plan requirement,
    docs/mllama_memory_plan.md) is numerically a no-op: identical loss and
    gradients, only the backward's recompute schedule changes."""
    import dataclasses

    _, params = hf_and_params
    pix, ids, ar_ids, ar_mask, xmask = _inputs()

    def loss_and_grads(cfg):
        model = MllamaForConditionalGeneration(cfg)
        return jax.jit(
            jax.value_and_grad(
                lambda p: model.loss(
                    p, jnp.asarray(ids), jnp.asarray(ids), jnp.asarray(pix),
                    jnp.asarray(ar_ids), jnp.asarray(ar_mask),
                    jnp.asarray(xmask),
                )
            )
        )(params)

    base_loss, base_grads = loss_and_grads(TINY)
    remat_cfg = dataclasses.replace(
        TINY, vision=dataclasses.replace(TINY.vision, remat="full")
    )
    remat_loss, remat_grads = loss_and_grads(remat_cfg)
    np.testing.assert_allclose(float(base_loss), float(remat_loss), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(base_grads), jax.tree.leaves(remat_grads)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_text_group_pattern_regular_and_irregular():
    """The grouped scan layout engages exactly when the cross-attn layers
    form the HF-regular xpos + g*k pattern (11B: stride 5, offset 3); an
    irregular config falls back to the per-layer list."""
    import dataclasses

    from neuronx_distributed_llama3_2_tpu.models.mllama import (
        MLLAMA_CONFIGS,
        text_group_pattern,
    )

    big = MLLAMA_CONFIGS["llama3.2-11b-vision"].text
    assert text_group_pattern(big) == (8, 5, 3)
    assert text_group_pattern(TINY.text) == (2, 2, 1)
    irregular = dataclasses.replace(big, cross_attention_layers=(3, 8, 14))
    assert text_group_pattern(irregular) is None
    # irregular configs still construct + run (the list/loop fallback)
    irr_tiny = dataclasses.replace(
        TINY, text=dataclasses.replace(
            TINY.text, cross_attention_layers=(1, 2)
        )
    )
    model = MllamaForConditionalGeneration(irr_tiny)
    params = model.init(jax.random.key(0))
    assert isinstance(params["layers"], list)
    pix, ids, ar_ids, ar_mask, xmask = _inputs()
    logits = jax.jit(
        lambda p: model(
            p, jnp.asarray(ids), jnp.asarray(pix), jnp.asarray(ar_ids),
            jnp.asarray(ar_mask), jnp.asarray(xmask),
        )
    )(params)
    assert np.isfinite(np.asarray(logits)).all()


def test_mllama_under_tp_sequence_parallel(hf_and_params):
    """tp=4 + Megatron SP over the text stream matches the unsharded
    logits — the sharding layout the 11B memory plan depends on
    (docs/mllama_memory_plan.md: the Lt·S activation term divides by tp)."""
    _, params = hf_and_params
    pix, ids, ar_ids, ar_mask, xmask = _inputs()
    model = MllamaForConditionalGeneration(TINY)
    ref = jax.jit(model.__call__)(
        params, jnp.asarray(ids), jnp.asarray(pix), jnp.asarray(ar_ids),
        jnp.asarray(ar_mask), jnp.asarray(xmask),
    )
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=4, sequence_parallel=True
    )
    sharded = shard_pytree(params, model.specs())
    out = jax.jit(model.__call__)(
        sharded, jnp.asarray(ids), jnp.asarray(pix), jnp.asarray(ar_ids),
        jnp.asarray(ar_mask), jnp.asarray(xmask),
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4, rtol=1e-3
    )


def test_text_group_pattern_rejects_all_cross_layers():
    """k=1 (every layer cross-attn) would pack an EMPTY plain stack — the
    pattern must reject it so init falls back to the list layout instead
    of crashing in _stack_trees([])."""
    import dataclasses

    from neuronx_distributed_llama3_2_tpu.models.mllama import (
        text_group_pattern,
    )

    all_cross = dataclasses.replace(
        TINY.text, num_hidden_layers=2, cross_attention_layers=(0, 1)
    )
    assert text_group_pattern(all_cross) is None
    cfg = dataclasses.replace(TINY, text=all_cross)
    model = MllamaForConditionalGeneration(cfg)
    params = model.init(jax.random.key(0))
    assert isinstance(params["layers"], list) and len(params["layers"]) == 2


@pytest.mark.xfail(
    compat.is_legacy_jax(),
    # Triage (jax 0.4.x line only): tp=8 does not divide num_heads=4, so
    # every flat tp layout in the attention stack lands mid-head, and the
    # 0.4.x CPU SPMD partitioner resolves those boundaries with reduction
    # reorderings that drift ~3e-3 in fp32 — patching
    # model_parallel_is_initialized() to False makes the same sharded
    # params match the reference EXACTLY, and forcing
    # tensor_parallel_size_or()->1 (GQAQKV replicated-heads fallback off)
    # halves the error, so the miscompile is in the partitioner's mid-head
    # handling, not repo logic (same class as the kv_flat_sharded guard in
    # parallel/layers.py). A compat.py shim that rounds activation
    # constraints down to head-aligned layouts (replicate instead of
    # mid-head shard) when is_legacy_jax() would close it; newer
    # partitioners handle mid-head boundaries exactly, so the test must
    # pass there — hence strict.
    reason="0.4.x SPMD partitioner miscompiles mid-head tp layouts "
    "(tp=8 > num_heads=4); see comment",
    strict=True,
)
def test_mllama_tp_with_indivisible_vocab(hf_and_params):
    """When tp doesn't divide the vocab (tp=16 with the 128256+8-row
    embedding — the 11B fitting config's blocker), the embed falls back to
    embedding-dim sharding and the head to input-dim sharding; logits must
    match the unsharded model exactly. Simulated here with a vocab that
    tp=8 does not divide."""
    import dataclasses

    _, params = hf_and_params
    pix, ids, ar_ids, ar_mask, xmask = _inputs()

    # TINY vocab 128: divisible by 8. Test the fallback decision logic on
    # a config whose vocab is NOT: trim both tables to vocab 124.
    cfg = dataclasses.replace(
        TINY, text=dataclasses.replace(TINY.text, vocab_size=124)
    )
    model = MllamaForConditionalGeneration(cfg)
    p124 = dict(params)
    p124["embed"] = {"embedding": params["embed"]["embedding"][: 124 + 8]}
    p124["lm_head"] = {"kernel": params["lm_head"]["kernel"][:, :124]}
    ids124 = np.minimum(ids, 123)

    ref = jax.jit(model.__call__)(
        p124, jnp.asarray(ids124), jnp.asarray(pix), jnp.asarray(ar_ids),
        jnp.asarray(ar_mask), jnp.asarray(xmask),
    )
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=8, sequence_parallel=True
    )
    specs = model.specs()
    from jax.sharding import PartitionSpec as _P

    # embed rows 132 % 8 != 0 -> embed-dim sharding; vocab 124 % 8 != 0 ->
    # input-dim (Row-parallel) head
    assert specs["embed"]["embedding"] == _P(None, "tp")
    assert specs["lm_head"]["kernel"] == _P("tp", None)
    sharded = shard_pytree(p124, specs)
    out = jax.jit(model.__call__)(
        sharded, jnp.asarray(ids124), jnp.asarray(pix), jnp.asarray(ar_ids),
        jnp.asarray(ar_mask), jnp.asarray(xmask),
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4, rtol=1e-3
    )


def test_mllama_loss_with_indivisible_vocab(hf_and_params):
    """The CE path under the Row-parallel head fallback: logits arrive
    replicated over tp, so parallel_cross_entropy must take the plain-CE
    branch rather than the vocab-sharded shard_map (which cannot split an
    indivisible vocab). Loss must match the unsharded model."""
    import dataclasses

    _, params = hf_and_params
    pix, ids, ar_ids, ar_mask, xmask = _inputs()
    cfg = dataclasses.replace(
        TINY, text=dataclasses.replace(TINY.text, vocab_size=124)
    )
    model = MllamaForConditionalGeneration(cfg)
    p124 = dict(params)
    p124["embed"] = {"embedding": params["embed"]["embedding"][: 124 + 8]}
    p124["lm_head"] = {"kernel": params["lm_head"]["kernel"][:, :124]}
    ids124 = jnp.asarray(np.minimum(ids, 123))

    def loss_of(p):
        return model.loss(
            p, ids124, ids124, jnp.asarray(pix), jnp.asarray(ar_ids),
            jnp.asarray(ar_mask), jnp.asarray(xmask),
        )

    ref = float(jax.jit(loss_of)(p124))
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=8, sequence_parallel=True
    )
    sharded = shard_pytree(p124, model.specs())
    got = float(jax.jit(loss_of)(sharded))
    np.testing.assert_allclose(got, ref, rtol=1e-5)
