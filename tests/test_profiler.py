"""Timeline / profiling tests (reference utils/timeline.py semantics: paired
start/end marks, per-step dump, disabled-when-no-path; VERDICT missing #8)."""

import json

import pytest

from neuronx_distributed_llama3_2_tpu.utils.profiler import Timeline, annotate


def _load(path):
    with open(path) as f:
        return json.load(f)


def test_timeline_chrome_trace_roundtrip(tmp_path):
    p = tmp_path / "tl.json"
    tl = Timeline(str(p))
    with tl.event("load_batch", cat="data"):
        pass
    tl.mark_event_start("train_step")
    tl.mark_event_end("train_step", loss=1.5)
    tl.step_end(0)
    with tl.event("save", cat="ckpt"):
        pass
    tl.close()
    events = _load(p)
    names = [e["name"] for e in events]
    assert names == ["load_batch", "train_step", "save"]
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in events)
    # categories map to distinct lanes
    tids = {e["cat"]: e["tid"] for e in events}
    assert len(set(tids.values())) == 3
    # args pass through
    assert events[1]["args"] == {"loss": 1.5}


def test_timeline_incremental_flush_stays_valid_json(tmp_path):
    p = tmp_path / "tl.json"
    tl = Timeline(str(p))
    for i in range(3):
        with tl.event("step"):
            pass
        tl.step_end(i)  # flush per step (reference mark_step_end)
        assert len(_load(p)) == i + 1
    tl.close()


def test_timeline_disabled_without_path():
    tl = Timeline(None)
    with tl.event("x"):
        pass
    tl.mark_event_start("y")
    tl.mark_event_end("y")
    tl.step_end()
    tl.close()  # no file io, no error


def test_timeline_unbalanced_marks_raise(tmp_path):
    tl = Timeline(str(tmp_path / "t.json"))
    tl.mark_event_start("a")
    with pytest.raises(ValueError):
        tl.mark_event_start("a")  # duplicate start (reference asserts too)
    with pytest.raises(ValueError):
        tl.mark_event_end("never-started")


def test_timeline_close_flushes_open_events(tmp_path):
    p = tmp_path / "t.json"
    tl = Timeline(str(p))
    tl.mark_event_start("dangling")
    tl.close()
    assert [e["name"] for e in _load(p)] == ["dangling"]


def test_annotate_usable_under_trace():
    # TraceAnnotation is a no-op outside an active profiler session; it must
    # still nest cleanly so call sites need no guards
    with annotate("region"):
        with annotate("inner"):
            pass
