"""One-dispatch steady state: the fused mixed-mode step program.

The contract under test (docs/serving.md "Fused mixed-mode step"):
``PagedConfig.fused_step`` packs decode lanes, speculative-verify rows,
and active prefill-chunk suffixes into ONE ``pmixed`` query-row grid over
the shared paged KV pool — one model dispatch per engine step — and the
emitted token streams stay **byte-identical** to the unfused engine (and
therefore to the dense oracle) across the whole serving matrix:
{gather, kernel} × {sync, async} × {spec, no-spec} × {chunked, whole}.

The tier-1 quartet is a pairwise-covering slice of that cube (the PR 9
matrix split); the remaining legs ride the opt-in slow tier. Alongside
parity: preemption/resume mid-fused-step, the dispatches-per-step
reduction on mixed traffic (the perf claim the knob exists for), the
graftscope row-role trace tags, and the host-sampling eligibility guard.
"""

import dataclasses

import numpy as np
import pytest

import jax

from neuronx_distributed_llama3_2_tpu.inference import (
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.serving import (
    NGramDrafter,
    PagedConfig,
    PagedServingEngine,
)

from tests.test_paged_serving import _dense_outputs, _prompts
from tests.test_speculative_serving import _paged, _rep_prompts, _run

TINY = LLAMA_CONFIGS["tiny"]
TINY_KERNEL = dataclasses.replace(TINY, use_paged_kernel=True)
GEN = GenerationConfig(max_new_tokens=8)

# mixed lengths straddling chunk=6: whole-prefill shorts, chunk-walk
# longs, and a 5th prompt that queues behind max_batch=4
_PLAIN_LENS = (5, 26, 9, 7, 12)
_REP_LENS = (9, 26, 12, 7, 15)


@pytest.fixture(scope="module")
def params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


_DENSE = {}


def _dense(params, prompts):
    key = tuple(tuple(p) for p in prompts)
    if key not in _DENSE:
        _DENSE[key] = _dense_outputs(params, prompts, GEN)
    return _DENSE[key]


def _leg_cfg(loop, spec, chunk, **kw):
    return PagedConfig(
        block_size=8, num_blocks=64,
        async_loop=(loop == "async"),
        spec_draft_tokens=(3 if spec == "spec" else 0),
        prefill_chunk_tokens=(6 if chunk == "chunk" else None),
        fused_step=True, **kw,
    )


def _leg_prompts(spec):
    if spec == "spec":
        return _rep_prompts(np.random.default_rng(31), _REP_LENS)
    return _prompts(np.random.default_rng(29), _PLAIN_LENS)


_S = pytest.mark.slow
# (model, loop, spec, chunk) — the tier-1 quartet covers every value of
# every dimension and all model×{loop,spec,chunk} + loop×chunk +
# spec×chunk pairs; the full cube runs under -m slow
CUBE = [
    ("kernel", "sync", "spec", "chunk"),
    ("gather", "async", "nospec", "chunk"),
    ("kernel", "async", "nospec", "whole"),
    ("gather", "sync", "spec", "whole"),
    pytest.param("kernel", "sync", "nospec", "chunk", marks=_S),
    pytest.param("kernel", "sync", "spec", "whole", marks=_S),
    pytest.param("kernel", "sync", "nospec", "whole", marks=_S),
    pytest.param("kernel", "async", "spec", "chunk", marks=_S),
    pytest.param("kernel", "async", "spec", "whole", marks=_S),
    pytest.param("kernel", "async", "nospec", "chunk", marks=_S),
    pytest.param("gather", "sync", "spec", "chunk", marks=_S),
    pytest.param("gather", "sync", "nospec", "chunk", marks=_S),
    pytest.param("gather", "sync", "nospec", "whole", marks=_S),
    pytest.param("gather", "async", "spec", "chunk", marks=_S),
    pytest.param("gather", "async", "spec", "whole", marks=_S),
    pytest.param("gather", "async", "nospec", "whole", marks=_S),
]


@pytest.mark.parametrize(
    "model,loop,spec,chunk",
    CUBE,
    ids=[
        "-".join(c.values if hasattr(c, "values") else c) for c in CUBE
    ],
)
def test_fused_token_parity(params, model, loop, spec, chunk):
    """Every leg: the fused engine's outputs equal the dense oracle (the
    unfused paged engines are pinned to the same oracle by their own
    suites, so this is transitively fused == unfused). Teardown inside
    ``_run`` keeps the invariant auditor, the block-pool leak check, and
    the program audit (GC001-GC008, including the pmixed no-gather and
    zero-upload checks) on every leg."""
    model_cfg = TINY_KERNEL if model == "kernel" else TINY
    drafter = NGramDrafter() if spec == "spec" else None
    prompts = _leg_prompts(spec)
    paged = _paged(
        params, GEN, _leg_cfg(loop, spec, chunk), model_cfg, drafter=drafter
    )
    out = _run(paged, prompts)
    assert out == _dense(params, prompts)
    if chunk == "chunk":
        # chunk walks rode the one-dispatch grid, never a psfx program
        assert paged.metrics.mixed_dispatches > 0
        assert not any(
            k[0] == "psfx" for k in paged.program_registry()
        )
    if spec == "spec":
        assert paged.metrics.draft_tokens > 0


def test_fused_preempt_resume_mid_step(params):
    """An older lane's decode growth exhausts the tight pool while a
    younger request is mid-chunk-walk INSIDE the mixed grid: the victim
    is requeued, re-admits through the fused path, and final outputs
    still match dense."""
    gen = GenerationConfig(max_new_tokens=8)
    rng = np.random.default_rng(21)
    pa = rng.integers(0, TINY.vocab_size, size=(8,)).tolist()
    pb = rng.integers(0, TINY.vocab_size, size=(30,)).tolist()
    paged = _paged(
        params, gen,
        PagedConfig(
            block_size=4, num_blocks=12, decode_reserve_blocks=1,
            prefill_chunk_tokens=4, fused_step=True,
        ),
    )
    preempted = []  # (rid, was_prefilling) at preemption time
    orig = paged._preempt

    def spy(req):
        preempted.append((req.rid, req.prefilling))
        orig(req)

    paged._preempt = spy
    ra = paged.submit(pa)
    rb = paged.submit(pb)
    out = _run(paged, [])
    assert (rb, True) in preempted, preempted
    assert paged.request_info(rb)["preemptions"] >= 1
    assert paged.metrics.mixed_dispatches > 0
    assert out == _dense_outputs(params, [pa, pb], gen)
    del ra


def _staggered(params, fused):
    """Mixed-traffic soak: long prompts arriving while earlier lanes are
    decoding, so unfused steps pay a psfx dispatch AND a decode dispatch
    while fused steps pay one pmixed."""
    paged = _paged(
        params, GEN,
        PagedConfig(
            block_size=8, num_blocks=64, prefill_chunk_tokens=6,
            fused_step=fused, trace_enabled=fused, trace_buffer_steps=128,
        ),
        TINY_KERNEL,
    )
    prompts = _prompts(np.random.default_rng(9), (21, 25, 18, 23))
    paged.submit(prompts[0])
    for p in prompts[1:]:
        paged.step()
        paged.step()
        paged.submit(p)
    out = _run(paged, [])
    return paged, out


def test_fused_reduces_dispatches_per_step_on_mixed_traffic(params):
    """The perf claim: on overlapped prefill+decode traffic the fused
    engine's model-dispatch-per-step ratio drops strictly below the
    unfused engine's (whose prefill chunks and decode are separate
    dispatches), while tokens stay identical. Also pins the
    ``dispatches_per_step`` snapshot gauge and the graftscope row-role
    tags (decode/verify/prefill row counts per fused dispatch)."""
    fused, out_f = _staggered(params, fused=True)
    unfused, out_u = _staggered(params, fused=False)
    assert out_f == out_u
    snap_f = fused.metrics.snapshot(fused.allocator, fused.index)
    snap_u = unfused.metrics.snapshot(unfused.allocator, unfused.index)
    assert snap_f["dispatches_per_step"] == pytest.approx(
        fused.metrics.compute_dispatches
        / max(fused.metrics.engine_steps, 1),
        abs=1e-4,
    )
    assert snap_f["dispatches_per_step"] < snap_u["dispatches_per_step"]
    assert fused.metrics.mixed_dispatches > 0
    assert unfused.metrics.mixed_dispatches == 0
    # every fused dispatch slice names how many rows each role packed
    mixed = [
        e for e in fused.tracer.chrome_events()
        if e["name"] == "dispatch" and e["args"].get("mode") == "mixed"
    ]
    assert mixed
    for e in mixed:
        a = e["args"]
        assert a["prefill_rows"] > 0  # abstention never dispatches pmixed
        assert a["decode_rows"] >= 0 and a["verify_rows"] >= 0
        assert (
            a["lanes"]
            == a["prefill_rows"] + a["decode_rows"] + a["verify_rows"]
        )
        assert a["prefill_tokens"] > 0
    # at least one fused step packed prefill rows WITH live decode lanes
    assert any(
        e["args"]["decode_rows"] + e["args"]["verify_rows"] > 0
        for e in mixed
    )


def test_fused_rejects_host_sampling(params):
    """Eligibility guard: fused_step needs per-lane device sampling for
    non-greedy configs (host sampling would re-upload every step); the
    constructor must refuse loudly rather than silently degrade."""
    gen = GenerationConfig(
        max_new_tokens=4,
        sampling=dataclasses.replace(GEN.sampling, greedy=False,
                                     temperature=0.7),
    )
    eng = InferenceEngine(
        TINY, params, max_batch=2, max_seq_len=32, buckets=[8]
    )
    with pytest.raises(ValueError, match="fused_step"):
        PagedServingEngine(
            eng, gen,
            PagedConfig(
                block_size=8, num_blocks=16, prefill_chunk_tokens=4,
                fused_step=True,
            ),
        )
    # same config with on-device sampling is legal
    PagedServingEngine(
        eng, gen,
        PagedConfig(
            block_size=8, num_blocks=16, prefill_chunk_tokens=4,
            fused_step=True, on_device_sampling=True,
        ),
    )
