"""Unit tests for the serving block pool: refcount / copy-on-write /
LRU-eviction invariants, and the radix prefix index over it.

Pure-Python (no JAX programs): the allocator and index are host-side
bookkeeping; the pool *data* paths are covered by tests/test_paged_serving.py.
"""

import pytest

from neuronx_distributed_llama3_2_tpu.serving import (
    NULL_BLOCK,
    BlockAllocator,
    RadixPrefixIndex,
)

# ---------------------------------------------------------------------------
# BlockAllocator
# ---------------------------------------------------------------------------


def test_alloc_never_returns_null_block_and_exhausts_to_none():
    a = BlockAllocator(num_blocks=4, block_size=8)
    got = [a.alloc() for _ in range(3)]
    assert NULL_BLOCK not in got
    assert sorted(got) == [1, 2, 3]
    assert a.alloc() is None  # every block held by an active request
    assert a.usable_blocks == 3
    assert a.free_blocks == 0


def test_refcount_release_returns_unregistered_to_free():
    a = BlockAllocator(num_blocks=4, block_size=8)
    b = a.alloc()
    a.incref(b)
    assert a.refcount(b) == 2
    a.release(b)
    assert a.refcount(b) == 1
    assert a.free_blocks == 2  # still held
    a.release(b)
    assert a.refcount(b) == 0
    assert a.free_blocks == 3  # unregistered -> straight back to free


def test_registered_release_parks_in_cache_and_incref_revives():
    a = BlockAllocator(num_blocks=4, block_size=8)
    b = a.alloc()
    a.register(b)
    a.release(b)
    assert a.free_blocks == 2
    assert a.cached_blocks == 1  # parked, KV intact
    assert a.available() == 3    # cached blocks still count as obtainable
    a.incref(b)                  # prefix hit revives it
    assert a.cached_blocks == 0
    assert a.refcount(b) == 1


def test_alloc_evicts_least_recently_released_first():
    a = BlockAllocator(num_blocks=4, block_size=8)
    b1, b2, b3 = a.alloc(), a.alloc(), a.alloc()
    for b in (b1, b2, b3):
        a.register(b)
    a.release(b2)  # oldest release = LRU victim
    a.release(b1)
    a.release(b3)
    got = a.alloc()
    assert got == b2
    assert a.evictions == 1
    assert not a.is_registered(b2)  # eviction drops the registration
    assert a.alloc() == b1
    assert a.alloc() == b3


def test_eviction_hook_frees_the_returned_subtree():
    a = BlockAllocator(num_blocks=5, block_size=8)
    b1, b2, b3 = a.alloc(), a.alloc(), a.alloc()
    for b in (b1, b2, b3):
        a.register(b)
        a.release(b)
    a.on_evict = lambda bid: [b2, b3] if bid == b1 else []
    # exhaust the free list, then force one eviction
    a.alloc()
    victim = a.alloc()
    assert victim == b1
    # b2/b3 were dropped alongside b1: back on the free list, unregistered
    assert a.cached_blocks == 0
    assert a.free_blocks == 2
    assert not a.is_registered(b2) and not a.is_registered(b3)
    assert a.evictions == 3


def test_eviction_hook_skips_blocks_held_by_active_requests():
    a = BlockAllocator(num_blocks=4, block_size=8)
    b1, b2 = a.alloc(), a.alloc()
    a.register(b1)
    a.register(b2)
    a.release(b1)  # parked; b2 stays active
    a.on_evict = lambda bid: [b2]
    a.alloc()  # takes the last free block
    got = a.alloc()  # evicts b1; hook names b2 but it has an active ref
    assert got == b1
    assert a.refcount(b2) == 1  # untouched
    assert not a.is_registered(b2)  # mapping is gone though


def test_unregister_frees_a_parked_block():
    a = BlockAllocator(num_blocks=3, block_size=8)
    b = a.alloc()
    a.register(b)
    a.release(b)
    assert a.cached_blocks == 1
    a.unregister(b)
    assert a.cached_blocks == 0
    assert a.free_blocks == 2


def test_cow_sole_unregistered_owner_writes_in_place():
    a = BlockAllocator(num_blocks=3, block_size=8)
    b = a.alloc()
    assert a.writable(b)
    assert a.copy_on_write(b) == (b, False)
    assert a.cow_copies == 0


def test_cow_shared_block_moves_to_private_copy():
    a = BlockAllocator(num_blocks=3, block_size=8)
    b = a.alloc()
    a.incref(b)  # second request shares it
    assert not a.writable(b)
    new, copied = a.copy_on_write(b)
    assert copied and new != b
    assert a.refcount(b) == 1  # our ref moved off
    assert a.refcount(new) == 1
    assert a.cow_copies == 1


def test_cow_registered_block_moves_even_at_ref_one():
    a = BlockAllocator(num_blocks=3, block_size=8)
    b = a.alloc()
    a.register(b)  # the index maps its contents: in-place write would
    assert not a.writable(b)  # corrupt future prefix hits
    new, copied = a.copy_on_write(b)
    assert copied and new != b
    assert a.cached_blocks == 1  # original parked, contents preserved


def test_cow_pool_exhaustion_returns_none():
    a = BlockAllocator(num_blocks=3, block_size=8)
    b1 = a.alloc()
    a.alloc()
    a.incref(b1)
    assert a.copy_on_write(b1) == (None, False)
    assert a.refcount(b1) == 2  # caller's ref untouched on failure


def test_stats_and_utilization():
    a = BlockAllocator(num_blocks=5, block_size=8)
    b1 = a.alloc()
    a.alloc()
    a.register(b1)
    a.release(b1)
    s = a.stats()
    assert s["active_blocks"] == 1
    assert s["cached_blocks"] == 1
    assert s["free_blocks"] == 2
    assert s["block_utilization"] == pytest.approx(1 / 4)
    assert a.available() == 3


def test_constructor_validation():
    with pytest.raises(ValueError):
        BlockAllocator(num_blocks=1, block_size=8)
    with pytest.raises(ValueError):
        BlockAllocator(num_blocks=4, block_size=0)


# ---------------------------------------------------------------------------
# RadixPrefixIndex
# ---------------------------------------------------------------------------


def _pool(n=32, bs=4):
    a = BlockAllocator(num_blocks=n, block_size=bs)
    return a, RadixPrefixIndex(a)


def test_match_on_empty_index():
    _, idx = _pool()
    assert idx.match([1, 2, 3]) == (0, [])


def test_insert_then_full_match():
    a, idx = _pool(bs=4)
    toks = [1, 2, 3, 4, 5, 6, 7, 8]
    blocks = [a.alloc(), a.alloc()]
    assert idx.insert(toks, blocks) == 2
    for b in blocks:
        assert a.is_registered(b)
    matched, got = idx.match(toks + [9, 9])
    assert matched == 8
    assert got == blocks


def test_partial_within_block_match():
    a, idx = _pool(bs=4)
    blocks = [a.alloc(), a.alloc()]
    idx.insert([1, 2, 3, 4, 5, 6, 7, 8], blocks)
    # diverges inside the second block: token-granular match, the caller
    # shares the block's leading rows and COWs before writing
    matched, got = idx.match([1, 2, 3, 4, 5, 6, 99, 99])
    assert matched == 6
    assert got == blocks


def test_partial_leaf_match_stops_the_walk():
    a, idx = _pool(bs=4)
    b1, b2 = a.alloc(), a.alloc()
    idx.insert([1, 2, 3, 4, 5, 6], [b1, b2])  # second block partial (2 toks)
    matched, got = idx.match([1, 2, 3, 4, 5, 6, 7, 8])
    assert matched == 6
    assert got == [b1, b2]


def test_leaf_upgrade_replaces_partial_with_fuller_block():
    a, idx = _pool(bs=4)
    b1, b2 = a.alloc(), a.alloc()
    idx.insert([1, 2, 3, 4, 5, 6], [b1, b2])
    a.release(b2)  # parked
    b3 = a.alloc()  # a later request materialized rows 4..7 fully
    idx.insert([1, 2, 3, 4, 5, 6, 7, 8], [b1, b3])
    assert not a.is_registered(b2)  # superseded leaf freed
    matched, got = idx.match([1, 2, 3, 4, 5, 6, 7, 8])
    assert matched == 8
    assert got == [b1, b3]


def test_insert_reuses_existing_nodes():
    a, idx = _pool(bs=4)
    b1, b2 = a.alloc(), a.alloc()
    idx.insert([1, 2, 3, 4], [b1])
    assert idx.insert([1, 2, 3, 4, 5, 6, 7, 8], [b1, b2]) == 1  # only b2 new
    assert idx.num_nodes == 2


def test_eviction_drops_whole_subtree():
    a, idx = _pool(n=4, bs=4)  # 3 usable blocks
    b1, b2, b3 = a.alloc(), a.alloc(), a.alloc()
    idx.insert([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12], [b1, b2, b3])
    for b in (b1, b2, b3):
        a.release(b)
    assert a.cached_blocks == 3
    got = a.alloc()  # evicts b1 (LRU) -> its whole chain is unreachable
    assert got == b1
    assert idx.num_nodes == 0
    assert a.cached_blocks == 0
    assert idx.match([1, 2, 3, 4]) == (0, [])


def test_hit_rate_counts_matched_tokens():
    a, idx = _pool(bs=4)
    idx.insert([1, 2, 3, 4], [a.alloc()])
    idx.match([1, 2, 3, 4])      # 4/4
    idx.match([9, 9, 9, 9])      # 0/4
    assert idx.hit_rate() == pytest.approx(0.5)
