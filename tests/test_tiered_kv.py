"""Tiered KV storage: the host-RAM spill tier behind the radix index.

Two layers (docs/serving.md "Tiered KV storage"):

- pure-host unit tests over the four-state block lifecycle
  (free/active/cached/spilled): :class:`HostTier` budget/LRU mechanics,
  the allocator's ``spill_hook`` eviction diversion, and the radix
  index's spilled-node bookkeeping (``mark_spilled`` / ``heal`` /
  ``invalidate_spilled`` / the insert-heal path);
- engine acceptance on the tiny CPU model: an eviction-heavy
  multi-tenant churn workload must produce **byte-identical token
  streams** with spill on vs off (restore-over-recompute is an
  optimization, never a numerics change) while actually restoring —
  including through an int8 pool (the scale tiles ride the spilled
  payload) and onto a copy-on-write extension of a restored block; the
  crossover knob declines restores when priced out; a host-tier fault
  falls back to re-prefill inside the victim's failure domain.
"""

import jax
import numpy as np
import pytest

from neuronx_distributed_llama3_2_tpu.inference import (
    GenerationConfig,
    InferenceEngine,
)
from neuronx_distributed_llama3_2_tpu.models.llama import (
    LLAMA_CONFIGS,
    LlamaForCausalLM,
)
from neuronx_distributed_llama3_2_tpu.serving import (
    BlockAllocator,
    FaultInjector,
    FaultPlan,
    PagedConfig,
    PagedServingEngine,
    RadixPrefixIndex,
    audit_engine,
)
from neuronx_distributed_llama3_2_tpu.serving.block_allocator import HostTier
from neuronx_distributed_llama3_2_tpu.serving.radix_index import SPILLED_BLOCK

TINY = LLAMA_CONFIGS["tiny"]


# ---------------------------------------------------------------------------
# HostTier
# ---------------------------------------------------------------------------


def test_host_tier_put_get_and_lru_budget_eviction():
    dropped = []
    t = HostTier(budget_bytes=100, on_evict=dropped.append)
    s1, s2, s3 = t.allocate_sid(), t.allocate_sid(), t.allocate_sid()
    assert (s1, s2, s3) == (0, 1, 2)  # sids are monotonic, never reused
    t.put_at(s1, ("a",), 40)
    t.put_at(s2, ("b",), 40)
    assert t.resident_bytes == 80 and t.num_entries == 2
    t.get(s1)  # touch: s2 becomes LRU
    t.put_at(s3, ("c",), 40)  # 120 > 100 -> evict s2
    assert dropped == [s2]
    assert t.evictions == 1
    assert not t.has(s2) and t.has(s1) and t.has(s3)
    assert t.resident_bytes == 80
    assert t.stats()["host_tier_evictions"] == 1
    assert t.pop(s1) == ("a",)
    t.drop(s3)  # silent drop: no on_evict
    assert dropped == [s2]
    assert t.resident_bytes == 0 and t.num_entries == 0


def test_host_tier_oversized_entry_evicts_itself():
    dropped = []
    t = HostTier(budget_bytes=10, on_evict=dropped.append)
    sid = t.allocate_sid()
    t.put_at(sid, ("big",), 50)  # cannot fit: immediately evicted
    assert dropped == [sid]
    assert t.resident_bytes == 0


def test_host_tier_budget_validation():
    with pytest.raises(ValueError):
        HostTier(budget_bytes=0)


# ---------------------------------------------------------------------------
# the four-state lifecycle: spill_hook + radix spilled nodes
# ---------------------------------------------------------------------------


def _pool(n=32, bs=4):
    a = BlockAllocator(num_blocks=n, block_size=bs)
    return a, RadixPrefixIndex(a)


def _spill_all(a, idx, tier):
    """Wire a spill hook that diverts every eviction into ``tier``."""
    def hook(bid):
        sid = tier.allocate_sid()
        if not idx.mark_spilled(bid, sid):
            return False
        tier.put_at(sid, (f"payload-{bid}",), 8)
        return True

    a.spill_hook = hook
    a.host_tier = tier


def test_spill_hook_diverts_eviction_and_match_stops_at_spilled():
    a, idx = _pool(n=4, bs=4)  # 3 usable blocks
    tier = HostTier(budget_bytes=1 << 20)
    _spill_all(a, idx, tier)
    b1, b2, b3 = a.alloc(), a.alloc(), a.alloc()
    toks = list(range(1, 13))
    idx.insert(toks, [b1, b2, b3])
    for b in (b1, b2, b3):
        a.release(b)
    got = a.alloc()  # evicts b1 (LRU) -> spilled, pool id recycled
    assert got == b1
    assert a.evictions == 1
    assert idx.num_spilled == 1 and idx.num_nodes == 2
    assert tier.num_entries == 1
    # four-state conservation: the spilled node holds no pool id
    assert a.leak_check() == []
    # match cannot hand out a spilled block...
    assert idx.match(toks) == (0, [])
    # ...but walk sees the full spilled-prefix chain
    matched, chain = idx.walk(toks)
    assert matched == 12
    assert chain[0].block == SPILLED_BLOCK and chain[0].sid == 0
    assert [n.block for n in chain[1:]] == [b2, b3]


def test_heal_rebinds_spilled_node_to_fresh_block():
    a, idx = _pool(n=4, bs=4)
    tier = HostTier(budget_bytes=1 << 20)
    _spill_all(a, idx, tier)
    b1 = a.alloc()
    idx.insert([1, 2, 3, 4], [b1])
    a.release(b1)
    a.alloc(), a.alloc(), a.alloc()  # force the eviction
    assert idx.num_spilled == 1
    (node,) = idx._spilled.values()
    idx.on_spill_drop = lambda sid: tier.drop(sid)
    nb = 1  # caller freed a lane; restore into a fresh id
    idx.heal(node, nb)
    assert idx.num_spilled == 0
    assert node.block == nb and node.sid == -1
    assert a.is_registered(nb)
    assert tier.num_entries == 0  # heal released the host payload
    assert idx.match([1, 2, 3, 4]) == (4, [nb])


def test_insert_heals_spilled_child_with_prefilled_block():
    a, idx = _pool(n=8, bs=4)
    tier = HostTier(budget_bytes=1 << 20)
    _spill_all(a, idx, tier)
    idx.on_spill_drop = lambda sid: tier.drop(sid)
    b1, b2 = a.alloc(), a.alloc()
    idx.insert([1, 2, 3, 4, 5, 6, 7, 8], [b1, b2])
    a.release(b1)
    a.release(b2)
    while a.free_blocks:
        a.alloc()
    a.alloc()  # evict+spill b1
    a.alloc()  # evict+spill b2
    assert idx.num_spilled == 2
    # a declined restore re-prefills the same prefix: insert must heal
    # the spilled chain in place of duplicating nodes
    nb1, nb2 = 1, 2
    assert idx.insert([1, 2, 3, 4, 5, 6, 7, 8], [nb1, nb2]) == 2
    assert idx.num_spilled == 0
    assert tier.num_entries == 0
    assert idx.match([1, 2, 3, 4, 5, 6, 7, 8]) == (8, [nb1, nb2])


def test_invalidate_spilled_drops_the_whole_downstream_run():
    a, idx = _pool(n=4, bs=4)
    tier = HostTier(budget_bytes=1 << 20)
    _spill_all(a, idx, tier)
    idx.on_spill_drop = lambda sid: tier.drop(sid)
    b1, b2, b3 = a.alloc(), a.alloc(), a.alloc()
    idx.insert(list(range(1, 13)), [b1, b2, b3])
    for b in (b1, b2, b3):
        a.release(b)
    a.alloc(), a.alloc(), a.alloc()  # spill the whole chain
    assert idx.num_spilled == 3
    sid0 = min(idx._spilled)  # shallowest = the failure domain's root
    idx.invalidate_spilled(sid0)
    assert idx.num_spilled == 0
    assert idx.num_nodes == 0
    assert tier.num_entries == 0
    assert a.leak_check() == []


def test_eviction_of_resident_child_under_spilled_parent():
    # parent spilled, child resident: evicting the child must not touch
    # the parent's host payload, and the chain stays walkable up to it
    a, idx = _pool(n=4, bs=4)
    tier = HostTier(budget_bytes=1 << 20)
    b1, b2 = a.alloc(), a.alloc()
    idx.insert([1, 2, 3, 4, 5, 6, 7, 8], [b1, b2])
    spilled_once = []

    def hook(bid):
        if bid == b1 and not spilled_once:
            sid = tier.allocate_sid()
            assert idx.mark_spilled(bid, sid)
            tier.put_at(sid, ("p",), 8)
            spilled_once.append(bid)
            return True
        return False  # child falls through to the plain drop path

    a.spill_hook = hook
    a.host_tier = tier
    a.alloc()  # consume the last free block so evictions engage
    a.release(b1)
    a.release(b2)
    a.alloc()  # evicts b1 -> spilled
    assert idx.num_spilled == 1
    a.alloc()  # evicts b2 -> plain drop (hook declines)
    assert idx.num_spilled == 1  # parent payload untouched
    assert tier.num_entries == 1
    assert a.leak_check() == []


# ---------------------------------------------------------------------------
# engine acceptance: byte-identity, COW-on-restored, int8, crossover, faults
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def params():
    return LlamaForCausalLM(TINY).init(jax.random.key(0))


def _churn_prompts(seed=7, n_fillers=4, prefix_tokens=20):
    """Shared prefix ending mid-block (20 = 2.5 blocks at block_size=8):
    the re-hit request must COW the restored partial leaf block."""
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, TINY.vocab_size, size=(prefix_tokens,)).tolist()
    fillers = [
        rng.integers(0, TINY.vocab_size, size=(20,)).tolist()
        for _ in range(n_fillers)
    ]
    return shared, fillers


def _run_churn(params, spill, kv_dtype="bf16", crossover=1e9, injector=None):
    """Seed a shared prefix, churn the pool past eviction, re-hit the
    prefix twice with different mid-block tails. Returns (outs, engine)."""
    gen = GenerationConfig(max_new_tokens=4)
    eng = PagedServingEngine(
        InferenceEngine(
            TINY, params, max_batch=2, max_seq_len=64, buckets=[8, 16, 32]
        ),
        gen,
        PagedConfig(
            block_size=8, num_blocks=12, kv_cache_dtype=kv_dtype,
            spill_enabled=spill,
            host_tier_bytes=(1 << 30) if spill else 0,
            restore_crossover=crossover if spill else 1.0,
        ),
        injector=injector,
    )
    shared, fillers = _churn_prompts()
    outs = {}
    eng.submit(shared + [1, 2])
    outs.update(eng.run_to_completion())
    for f in fillers:
        eng.submit(f)
    outs.update(eng.run_to_completion())
    eng.submit(shared + [3, 4])
    eng.submit(shared + [5, 6])
    outs.update(eng.run_to_completion())
    assert audit_engine(eng) == []
    assert eng.allocator.leak_check() == []
    return outs, eng


@pytest.fixture(scope="module")
def bf16_baseline(params):
    return _run_churn(params, spill=False)[0]


def test_spill_restore_byte_identity_and_cow_on_restored_block(
    params, bf16_baseline
):
    outs, eng = _run_churn(params, spill=True)
    assert outs == bf16_baseline  # restore is invisible to the tokens
    m = eng.metrics
    assert m.blocks_spilled > 0
    assert m.restore_hits > 0 and m.blocks_restored > 0
    assert m.restore_bytes > 0 and m.restore_uploads > 0
    # the 20-token prefix ends mid-block: extending past a restored
    # partial leaf must go through copy-on-write, never write in place
    assert eng.allocator.cow_copies > 0
    # conservation held with a populated host tier (the audit above ran
    # with spilled payloads resident); spill bookkeeping is consistent
    assert eng.index.num_spilled == len(eng.index._spilled)
    snap = m.snapshot(eng.allocator, eng.index)
    assert snap["restore_hit_rate"] > 0
    assert snap["host_tier_bytes"] >= 0


def test_quantized_scale_tiles_round_trip_through_spill(params):
    base, _ = _run_churn(params, spill=False, kv_dtype="int8")
    outs, eng = _run_churn(params, spill=True, kv_dtype="int8")
    m = eng.metrics
    assert m.restore_hits > 0
    # byte-identity through an int8 pool proves the k/v scale tiles
    # rode the spilled payload and restored exactly (a lost or reordered
    # scale tile would change the dequantized logits)
    assert outs == base


def test_restore_crossover_declines_and_audit_spots_lost_payload(params):
    # crossover 0 prices every restore out: the engine must fall back to
    # re-prefill (insert() heals the spilled chain) with identical tokens
    outs, eng = _run_churn(params, spill=True, crossover=0.0)
    m = eng.metrics
    assert m.restore_hits == 0 and m.blocks_restored == 0
    assert m.restore_declined > 0
    assert outs == _run_churn(params, spill=False)[0]
    # invariant 9 teeth: losing a host payload behind the index's back
    # (bypassing the drop hooks) is a detectable violation
    if eng.index.num_spilled and eng.host_tier.num_entries:
        sid = next(iter(eng.index._spilled))
        if eng.host_tier.has(sid):
            eng.host_tier._entries.pop(sid)
            assert any("payload" in v for v in audit_engine(eng))


def test_host_tier_fault_falls_back_to_reprefill(params, bf16_baseline):
    inj = FaultInjector(FaultPlan(seed=3, host_tier_rate=1.0))
    outs, eng = _run_churn(params, spill=True, injector=inj)
    m = eng.metrics
    assert inj.counts["host_tier"] >= 1
    assert m.restore_fallbacks >= 1
    assert m.restore_hits == 0  # every attempt was corrupted
    # the fallback re-prefills inside the victim's failure domain:
    # every token stream stays byte-identical to the fault-free baseline
    assert outs == bf16_baseline


def test_spill_config_validation(params):
    with pytest.raises(ValueError, match="host_tier_bytes"):
        PagedServingEngine(
            InferenceEngine(
                TINY, params, max_batch=2, max_seq_len=64, buckets=[8, 16]
            ),
            GenerationConfig(max_new_tokens=2),
            PagedConfig(block_size=8, num_blocks=12, spill_enabled=True),
            precompile=False,
        )
    with pytest.raises(ValueError, match="prefix"):
        PagedServingEngine(
            InferenceEngine(
                TINY, params, max_batch=2, max_seq_len=64, buckets=[8, 16]
            ),
            GenerationConfig(max_new_tokens=2),
            PagedConfig(
                block_size=8, num_blocks=12, spill_enabled=True,
                host_tier_bytes=1 << 20, enable_prefix_caching=False,
            ),
            precompile=False,
        )
