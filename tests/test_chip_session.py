"""Tests for the measurement tooling itself (VERDICT r4 #3).

The chip-session orchestrator is the one tool whose job is to never waste
a healthy-relay window, and bench.py's post-headline hook is how the
driver's ``python bench.py`` invocation banks the whole session — both
must be exercised by the suite, not just trusted. Stages here are stubbed
(fast fake subprocesses / injected runners); the real stage scripts get
separate --cpu --quick smoke tests.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel)
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def cs():
    return _load("chip_session_mod", "scripts/chip_session.py")


@pytest.fixture(scope="module")
def bench():
    return _load("bench_session_mod", "bench.py")


RECORD_KEYS = {"stage", "status", "rc", "seconds", "parsed", "tail"}


def _stub_runner(script):
    """Stage runner returning scripted records (no subprocesses)."""

    def run(name, argv, timeout_s):
        rec = {
            "stage": name,
            "status": "ok",
            "rc": 0,
            "seconds": 0.1,
            "parsed": {"metric": name},
            "tail": f"{name} done",
        }
        rec.update(script.get(name, {}))
        return rec

    return run


def test_run_session_record_schema_and_file(tmp_path, cs):
    out = tmp_path / "session.jsonl"
    stages = [("a", ["true"], 10), ("b", ["true"], 10)]
    results, aborted = cs.run_session(
        stages, deadline_s=60, out_path=str(out), stage_runner=_stub_runner({})
    )
    assert aborted is None
    assert [r["stage"] for r in results] == ["a", "b"]
    for r in results:
        assert set(r) == RECORD_KEYS
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert lines[0]["stages"] == ["a", "b"]  # session header
    assert [ln["stage"] for ln in lines[1:]] == ["a", "b"]


def test_run_session_aborts_on_probe_failure(tmp_path, cs):
    """A dead relay must abort the session immediately — nothing downstream
    can succeed, and burning stage timeouts against a dead backend is the
    round-2 failure mode."""
    out = tmp_path / "s.jsonl"
    stages = [("probe", ["true"], 10), ("bench", ["true"], 10)]
    results, aborted = cs.run_session(
        stages,
        deadline_s=60,
        out_path=str(out),
        stage_runner=_stub_runner({"probe": {"status": "timeout", "rc": None}}),
    )
    assert aborted is not None and "probe" in aborted
    assert [r["stage"] for r in results] == ["probe"]  # bench never ran
    assert json.loads(out.read_text().splitlines()[-1])["aborted"] == aborted


def test_run_session_deadline_exhaustion(tmp_path, cs):
    import time as _time

    def slow_runner(name, argv, timeout_s):
        _time.sleep(0.2)
        return _stub_runner({})(name, argv, timeout_s)

    results, aborted = cs.run_session(
        [("a", ["true"], 10), ("b", ["true"], 10)],
        deadline_s=30.2,  # stage a's 0.2 s leaves < 30 s — b must not start
        out_path=str(tmp_path / "s.jsonl"),
        stage_runner=slow_runner,
    )
    assert [r["stage"] for r in results] == ["a"]
    assert "deadline exhausted" in aborted and "b" in aborted


def test_run_session_streams_records_and_echoes_headline(tmp_path, cs, capsys):
    """Bank-as-you-go: every record prints as it completes, and the
    headline is re-echoed after each one so the stream's last complete
    JSON line is the driver metric wherever a kill lands."""
    headline = json.dumps({"metric": "m", "value": 1.0, "vs_baseline": 1.01})
    cs.run_session(
        [("a", ["true"], 10), ("b", ["true"], 10)],
        deadline_s=60,
        out_path=str(tmp_path / "s.jsonl"),
        stream=sys.stdout,
        echo_line=headline,
        stage_runner=_stub_runner({}),
    )
    out_lines = capsys.readouterr().out.strip().splitlines()
    parsed = [json.loads(ln) for ln in out_lines]
    assert [p.get("stage", "HEADLINE") for p in parsed] == [
        "a", "HEADLINE", "b", "HEADLINE"
    ]
    assert parsed[-1] == json.loads(headline)


def test_run_stage_disables_nested_session_and_bounds_timeout(cs):
    """Real-subprocess checks: stage children inherit BENCH_SESSION=0 (the
    bench stage of a manual session must not recurse into its own
    session), and a hung stage is killed at its bound with a parseable
    timeout record."""
    rec = cs.run_stage(
        "envcheck",
        [sys.executable, "-c",
         "import os, json; print(json.dumps({'sess': os.environ['BENCH_SESSION']}))"],
        30,
    )
    assert rec["status"] == "ok" and rec["parsed"] == {"sess": "0"}

    rec = cs.run_stage(
        "hang", [sys.executable, "-c", "import time; time.sleep(30)"], 1.0
    )
    assert rec["status"] == "timeout" and rec["rc"] is None
    assert set(rec) == RECORD_KEYS


def test_run_stage_records_launch_error(cs):
    rec = cs.run_stage("gone", ["/nonexistent/stage-script"], 5)
    assert rec["status"] == "launch_error"
    assert set(rec) == RECORD_KEYS


def test_bench_ok_path_invokes_post_session_with_headline(bench, capsys):
    """main_with_retries must hand the post-session hook the headline line
    (not the whole stdout) and the loop's start time."""
    good = json.dumps({"metric": bench.METRIC_NAME, "value": 1.0,
                       "unit": "tokens/s", "vs_baseline": 1.02})
    seen = {}

    def post(headline, start):
        seen["headline"] = headline
        seen["start"] = start

    bench.main_with_retries(
        attempts=1, backoff_s=0, deadline_s=30, attempt_timeout_s=10,
        launch=lambda t: ("ok", "# chatter\n" + good + "\n", ""),
        probe=lambda: "ok",
        post_session=post,
    )
    # the headline handed to the session hook carries the preflight stamp
    assert json.loads(seen["headline"]) == {**json.loads(good),
                                            "preflight": "ok"}
    assert isinstance(seen["start"], float)
    capsys.readouterr()


def test_bench_failure_path_skips_post_session(bench, capsys):
    called = []
    with pytest.raises(SystemExit):
        bench.main_with_retries(
            attempts=1, backoff_s=0, deadline_s=30, attempt_timeout_s=10,
            launch=lambda t: ("error", "", "UNAVAILABLE: down"),
            probe=lambda: "backend_init_timeout",
            post_session=lambda *a: called.append(a),
        )
    assert not called
    capsys.readouterr()


def test_post_session_env_gate_and_budget(bench, monkeypatch):
    """BENCH_SESSION=0 and an exhausted budget must both skip the session
    without importing chip_session (a broken session can never cost the
    headline)."""
    import time as _time

    def boom():
        raise AssertionError("chip_session must not be loaded")

    monkeypatch.setattr(bench, "_load_chip_session", boom)
    monkeypatch.setenv("BENCH_SESSION", "0")
    bench._post_session("{}", _time.monotonic())

    monkeypatch.setenv("BENCH_SESSION", "1")
    monkeypatch.setenv("BENCH_SESSION_DEADLINE_S", "100")
    bench._post_session("{}", _time.monotonic() - 99.0)  # < 180 s left


def test_post_session_runs_stages_minus_probe_and_bench(bench, monkeypatch,
                                                        tmp_path):
    """The post-headline session must run the chip-session stage list
    minus probe (headline success already proved the backend) and bench
    (just ran), streaming to stdout with the headline echoed."""
    calls = {}

    class FakeCS:
        STAGES = [("probe", ["p"], 1), ("bench", ["b"], 1),
                  ("mfu_sweep", ["m"], 1), ("head_ab", ["h"], 1)]

        @staticmethod
        def run_session(stages, deadline_s, out_path, stream, echo_line):
            calls["stages"] = [s[0] for s in stages]
            calls["deadline"] = deadline_s
            calls["echo"] = echo_line
            return [], None

    monkeypatch.setattr(bench, "_load_chip_session", lambda: FakeCS)
    monkeypatch.delenv("BENCH_SESSION", raising=False)
    monkeypatch.setenv("BENCH_SESSION_DEADLINE_S", "1000")
    import time as _time

    bench._post_session('{"metric": "x"}', _time.monotonic())
    assert calls["stages"] == ["mfu_sweep", "head_ab"]
    assert 900 < calls["deadline"] <= 1000
    assert calls["echo"] == '{"metric": "x"}'


def test_session_stage_list_covers_verdict_requirements(cs):
    """The banked-session contract (VERDICT r4 #1 + #5): MFU margin,
    chip-side TTFT 1B/3B, churn, kernel gate, long-context, ring-step,
    and the two A/B default gates must all be staged."""
    names = {s[0] for s in cs.STAGES}
    assert {
        "probe", "bench", "mfu_sweep", "ttft_prefill_1b", "ttft_prefill_3b",
        "churn_1b", "kernel_gate", "long_context", "ring_step_timing",
        "head_ab", "ring_ab",
    } <= names


@pytest.mark.parametrize(
    "which,timeout",
    [
        ("head", 180),
        # tier-1 budget: the ring leg doubles the head leg's coverage of
        # the stage driver; it rides in the slow tier
        pytest.param("ring", 300, marks=pytest.mark.slow),
    ],
)
def test_ab_stage_smoke(which, timeout):
    """The A/B stage scripts run end-to-end on the CPU plumbing tier and
    emit one parseable JSON record with the comparison fields."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "ab_stage.py"),
         "--which", which, "--cpu", "--quick", "--iters", "1"],
        capture_output=True, text=True, timeout=timeout,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    if which == "head":
        assert rec["ab"] == "head_sequence_split"
        assert rec["ici_unmeasured"] is True
        assert rec["split_fwdbwd_ms"] > 0 and rec["unsplit_fwdbwd_ms"] > 0
    else:
        assert rec["ab"] == "ring_zigzag_vs_contiguous"
        row = rec["rows"][0]
        assert row["critical_contiguous_fwdbwd_ms"] > 0
        assert row["critical_zigzag_fwdbwd_ms"] > 0


def test_mllama_memory_plan_skip_measure_smoke():
    """The 11B memory-plan script's exact accounting path runs and emits
    the static byte plan (VERDICT r4 #3; the full measured path is the
    docs/mllama_memory_plan.md deliverable)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "mllama_memory_plan.py"),
         "--skip-measure"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    exact = rec["exact"]
    assert exact["mesh"] == {"tp": 8, "dp": 8}
    assert exact["n_params_B"] > 9  # the 11B model, not a stub
    for k in ("bf16_params_GB_per_chip", "zero1_master_fp32_GB_per_chip",
              "zero1_moments_fp32_GB_per_chip", "grads_GB_per_chip",
              "static_total_GB_per_chip"):
        assert exact[k] > 0
    assert exact["static_total_GB_per_chip"] < rec["hbm_per_chip_GB"]


def test_chipbench_time_fn_consumes_all_grad_outputs():
    """The shared timer must keep EVERY output leaf live: jax.grad with
    multiple argnums returns sibling cotangents, and consuming only the
    first would let XLA dead-code the others' backward (under-measuring,
    e.g., the whole dW matmul of a head timing). Verify by checking the
    compiled chained program's flop count grows when a second cotangent
    is present."""
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_llama3_2_tpu.utils.chipbench import time_fn

    def loss(h, w):
        return jnp.sum((h @ w) ** 2)

    h = jnp.ones((64, 64), jnp.float32)
    w = jnp.ones((64, 64), jnp.float32)

    def cost_of(fn):
        def chained(*a):
            def body(carry, _):
                out = fn(carry, *a[1:])
                nudge = jnp.asarray(0.0, jnp.float32)
                for leaf in jax.tree.leaves(out):
                    nudge = nudge + jnp.ravel(leaf)[0]
                return carry + (nudge * 1e-12).astype(a[0].dtype), None

            carry, _ = jax.lax.scan(body, a[0], None, length=4)
            return carry

        from neuronx_distributed_llama3_2_tpu.utils import compat

        return compat.cost_analysis(jax.jit(chained).lower(h, w).compile())["flops"]

    both = cost_of(jax.grad(loss, argnums=(0, 1)))
    just_h = cost_of(jax.grad(loss, argnums=(0,)))
    assert both > just_h * 1.3, (both, just_h)  # dW backward stayed live

    # and the public helper runs + returns a sane duration
    dt = time_fn(jax.grad(loss, argnums=(0, 1)), h, w, repeats=2)
    assert 0 < dt < 60


def test_run_session_reprobes_and_aborts_on_dead_relay(tmp_path, cs):
    """A relay that dies MID-session must not burn every remaining stage's
    timeout: after 2 consecutive stage failures a bare-probe runs, and a
    failed probe aborts the session."""
    calls = []

    def runner(name, argv, timeout_s):
        calls.append(name)
        status = "ok" if name == "a" else "timeout"
        return {"stage": name, "status": status,
                "rc": 0 if status == "ok" else None, "seconds": 0.1,
                "parsed": None, "tail": ""}

    stages = [(n, ["x"], 10) for n in ("a", "b", "c", "d", "e")]
    results, aborted = cs.run_session(
        stages, deadline_s=60, out_path=str(tmp_path / "s.jsonl"),
        stage_runner=runner,
    )
    # a ok, b bad, c bad -> reprobe (fails) -> abort; d/e never run
    assert calls == ["a", "b", "c", "reprobe"]
    assert "relay died mid-session" in aborted
    assert [r["stage"] for r in results] == ["a", "b", "c", "reprobe"]


def test_run_session_reprobe_ok_continues(tmp_path, cs):
    """Consecutive stage failures with a HEALTHY backend are stage bugs,
    not an outage — the session must keep going after the probe passes."""
    calls = []

    def runner(name, argv, timeout_s):
        calls.append(name)
        status = "ok" if name in ("reprobe", "d") else "error"
        return {"stage": name, "status": status,
                "rc": 0 if status == "ok" else 1, "seconds": 0.1,
                "parsed": None, "tail": ""}

    stages = [(n, ["x"], 10) for n in ("b", "c", "d")]
    results, aborted = cs.run_session(
        stages, deadline_s=60, out_path=str(tmp_path / "s.jsonl"),
        stage_runner=runner,
    )
    assert aborted is None
    assert calls == ["b", "c", "reprobe", "d"]


def test_post_session_malformed_env_never_raises(bench, monkeypatch, capsys):
    """A malformed BENCH_SESSION_DEADLINE_S must not turn a healthy
    headline run into a nonzero exit (the driver keys on exit code)."""
    monkeypatch.delenv("BENCH_SESSION", raising=False)
    monkeypatch.setenv("BENCH_SESSION_DEADLINE_S", "2h")
    import time as _time

    bench._post_session('{"metric": "x"}', _time.monotonic())  # no raise
    err = capsys.readouterr().err
    assert "chip session failed" in err
