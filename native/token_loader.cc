// Native token-stream loader: mmap + background prefetch batch gather.
//
// The native counterpart of data/dataset.py's TokenDataset/DistributedDataLoader
// hot path (the role torch's C++ DataLoader workers play in the reference's
// pipeline, training_utils.py:99). The Python side stays in charge of
// *policy* — epoch shuffling, dp sharding, resume — and hands this library
// explicit sample indices; the library owns the *mechanism*: zero-copy mmap
// of the token file, int-width conversion, and a worker thread that gathers
// the next batch while the accelerator runs the current step.
//
// C ABI only (loaded via ctypes — no pybind11 dependency, per the build
// environment).  All functions are thread-compatible: one handle is driven
// by one Python thread.

#include <cstdint>
#include <cstring>
#include <condition_variable>
#include <fcntl.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Loader {
  int fd = -1;
  const uint8_t* base = nullptr;   // mmap base
  size_t map_len = 0;
  long long data_off = 0;          // byte offset of token 0 (.npy header)
  long long n_tokens = 0;
  int token_bytes = 4;             // 1/2/4/8 little-endian
  bool is_signed = true;

  // prefetch state
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv;
  std::vector<long long> pending;  // sample indices to gather
  int pending_seq = 0;
  std::vector<int32_t> ready;      // gathered batch
  bool job_posted = false;
  bool job_active = false;         // worker is mid-gather
  bool job_done = false;
  bool stop = false;

  ~Loader() {
    {
      std::lock_guard<std::mutex> g(mu);
      stop = true;
    }
    cv.notify_all();
    if (worker.joinable()) worker.join();
    if (base) munmap(const_cast<uint8_t*>(base), map_len);
    if (fd >= 0) close(fd);
  }

  inline int32_t token_at(long long i) const {
    const uint8_t* p = base + data_off + i * (long long)token_bytes;
    if (is_signed) {
      switch (token_bytes) {
        case 1: return (int32_t) * (const int8_t*)p;
        case 2: { int16_t v; memcpy(&v, p, 2); return v; }
        case 8: { int64_t v; memcpy(&v, p, 8); return (int32_t)v; }
        default: { int32_t v; memcpy(&v, p, 4); return v; }
      }
    }
    // unsigned: widen without sign-extension (uint32/64 wrap to int32 the
    // way numpy's astype(int32) does — parity with the python path)
    switch (token_bytes) {
      case 1: return (int32_t) * (const uint8_t*)p;
      case 2: { uint16_t v; memcpy(&v, p, 2); return (int32_t)v; }
      case 8: { uint64_t v; memcpy(&v, p, 8); return (int32_t)v; }
      default: { uint32_t v; memcpy(&v, p, 4); return (int32_t)v; }
    }
  }

  void gather(const long long* idx, int count, int seq, int32_t* out) const {
    for (int b = 0; b < count; ++b) {
      const long long start = idx[b] * (long long)seq;
      if (token_bytes == 4 && is_signed) {
        memcpy(out + (long long)b * seq,
               base + data_off + start * 4, (size_t)seq * 4);
      } else {
        for (int t = 0; t < seq; ++t)
          out[(long long)b * seq + t] = token_at(start + t);
      }
    }
  }

  void worker_loop() {
    std::unique_lock<std::mutex> lk(mu);
    while (true) {
      cv.wait(lk, [&] { return stop || job_posted; });
      if (stop) return;
      std::vector<long long> idx = std::move(pending);
      int seq = pending_seq;
      job_posted = false;
      job_active = true;
      ready.resize((size_t)idx.size() * seq);
      lk.unlock();
      gather(idx.data(), (int)idx.size(), seq, ready.data());
      lk.lock();
      job_active = false;
      job_done = true;
      cv.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// Open a raw little-endian token file region. Returns nullptr on failure.
void* tl_open(const char* path, long long data_off, long long n_tokens,
              int token_bytes, int is_signed) {
  if (token_bytes != 1 && token_bytes != 2 && token_bytes != 4 &&
      token_bytes != 8)
    return nullptr;
  auto* L = new Loader();
  L->is_signed = is_signed != 0;
  L->fd = open(path, O_RDONLY);
  if (L->fd < 0) { delete L; return nullptr; }
  struct stat st;
  if (fstat(L->fd, &st) != 0) { delete L; return nullptr; }
  const long long need = data_off + n_tokens * (long long)token_bytes;
  if (st.st_size < need) { delete L; return nullptr; }
  L->map_len = (size_t)st.st_size;
  void* m = mmap(nullptr, L->map_len, PROT_READ, MAP_PRIVATE, L->fd, 0);
  if (m == MAP_FAILED) { delete L; return nullptr; }
  L->base = (const uint8_t*)m;
  L->data_off = data_off;
  L->n_tokens = n_tokens;
  L->token_bytes = token_bytes;
  L->worker = std::thread([L] { L->worker_loop(); });
  return L;
}

void tl_close(void* h) { delete (Loader*)h; }

long long tl_num_tokens(void* h) { return ((Loader*)h)->n_tokens; }

// Synchronous gather of `count` samples of length `seq` into out (int32).
void tl_gather(void* h, const long long* idx, int count, int seq,
               int32_t* out) {
  ((Loader*)h)->gather(idx, count, seq, out);
}

// Post the next batch's indices; the worker gathers it in the background.
void tl_prefetch(void* h, const long long* idx, int count, int seq) {
  auto* L = (Loader*)h;
  std::lock_guard<std::mutex> g(L->mu);
  L->pending.assign(idx, idx + count);
  L->pending_seq = seq;
  L->job_posted = true;
  L->job_done = false;
  L->cv.notify_all();
}

// Wait for the posted batch and copy it out. Returns token count, or -1 if
// nothing was prefetched.
long long tl_wait(void* h, int32_t* out, long long out_capacity) {
  auto* L = (Loader*)h;
  std::unique_lock<std::mutex> lk(L->mu);
  // a job is outstanding if posted, mid-gather (active), or finished —
  // inferring only from posted/done races with the worker's take-window
  if (!L->job_done && !L->job_posted && !L->job_active) return -1;
  L->cv.wait(lk, [&] { return L->job_done; });
  const long long n = (long long)L->ready.size();
  if (n > out_capacity) return -1;
  memcpy(out, L->ready.data(), (size_t)n * 4);
  L->job_done = false;
  return n;
}

}  // extern "C"
