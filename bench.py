"""Benchmark: Llama-3.2 1B training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Throughput definition replicates the reference's
(examples/training/llama/training_utils.py:329-351: moving-window seqs/s,
converted here to tokens/sec/chip, the BASELINE.json primary metric).
``vs_baseline`` is measured/target where the target is the BASELINE.md MFU
north star (≥45% MFU) converted to tokens/sec for this chip+model, since the
reference repo publishes no absolute numbers (BASELINE.md).
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from neuronx_distributed_llama3_2_tpu.models import LLAMA_CONFIGS, LlamaForCausalLM
    from neuronx_distributed_llama3_2_tpu.trainer import (
        OptimizerConfig,
        TrainingConfig,
        initialize_parallel_model,
        make_train_step,
    )
    from neuronx_distributed_llama3_2_tpu.trainer.metrics import (
        mfu,
        train_flops_per_token,
    )

    model_cfg = dataclasses.replace(
        LLAMA_CONFIGS["llama3.2-1b"],
        remat="full",
        max_seq_len=2048,
        use_flash_attention=True,
        # tuned on v5e: large flash tiles amortize Mosaic per-program
        # overhead (sweep: 256x512 -> 41.7%, 1024x1024 -> 46.0% MFU);
        # chunk 256 beats 512 by ~1 point on the fused CE
        flash_block_q=1024,
        flash_block_kv=1024,
        loss_chunk_size=256,
    )
    batch, seq = 12, 2048

    # Single-chip 1B: pure-bf16 optimizer (no fp32 master — 12 bytes/param of
    # AdamW state does not fit 16G HBM next to the model; multi-chip ZeRO-1
    # restores fp32 state by sharding it over dp).
    config = TrainingConfig(
        optimizer=OptimizerConfig(
            zero_one_enabled=False,
            warmup_steps=1,
            use_master_weights=False,
            use_fp32_grad_acc=False,
            state_dtype="bfloat16",
        )
    )
    config.initialize()
    model = LlamaForCausalLM(model_cfg)
    state, _ = initialize_parallel_model(model, config)
    step = make_train_step(model, config)

    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, model_cfg.vocab_size, (batch, seq)),
        dtype=jnp.int32,
    )
    data = {"input_ids": ids, "labels": ids}

    # warmup / compile (block via host transfer: on the axon tunnel backend
    # block_until_ready returns before execution completes)
    state, metrics = step(state, data)
    loss0 = float(metrics["loss"])
    if not np.isfinite(loss0):
        raise RuntimeError(f"non-finite loss {loss0} on the bench step")

    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, data)
        float(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters

    tokens_per_sec = batch * seq / dt

    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(state.params))
    # v5e: 197 TFLOP/s bf16 peak
    peak = 197e12
    measured_mfu = mfu(
        tokens_per_sec,
        n_params,
        model_cfg.num_layers,
        model_cfg.hidden_size,
        seq,
        peak,
    )
    # target tokens/sec at the BASELINE.md 45%-MFU north star
    target_tps = 0.45 * peak / train_flops_per_token(
        n_params, model_cfg.num_layers, model_cfg.hidden_size, seq
    )

    print(
        json.dumps(
            {
                "metric": "llama3.2-1b_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(tokens_per_sec / target_tps, 4),
                "detail": {
                    "mfu": round(measured_mfu, 4),
                    "step_ms": round(dt * 1000, 2),
                    "batch": batch,
                    "seq": seq,
                    "n_params": n_params,
                    "chip": str(jax.devices()[0]),
                },
            }
        )
    )


def main_with_retries(attempts: int = 3, backoff_s: float = 60.0) -> None:
    """The tunneled dev chip's relay occasionally drops with UNAVAILABLE
    backend-init errors and recovers within minutes; retry so a transient
    flap doesn't cost the round's benchmark artifact."""
    for i in range(attempts):
        try:
            main()
            return
        except RuntimeError as e:
            transient = "UNAVAILABLE" in str(e) or "Unable to initialize" in str(e)
            if not transient or i == attempts - 1:
                raise
            # a mid-run drop leaves the parallel state initialized; clear it
            # or the retry dies on "already initialized" instead
            from neuronx_distributed_llama3_2_tpu.parallel import (
                state as parallel_state,
            )

            parallel_state.destroy_model_parallel()
            print(
                f"# backend unavailable (attempt {i + 1}/{attempts}): {e}; "
                f"retrying in {backoff_s:.0f}s",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(backoff_s)


if __name__ == "__main__":
    main_with_retries()
