"""Benchmark: Llama-3.2 1B training throughput on one TPU chip.

Prints the headline JSON line {"metric", "value", "unit", "vs_baseline"},
then — when the backend is healthy — spends the remaining session budget
banking every staged chip measurement (scripts/chip_session.py stages in
value order: MFU margin sweep, chip-side TTFT 1B/3B, head/ring A/B
default gates, kernel gate, churn, 32K long-context, ring-step timing),
appending each record to CHIP_SESSION.jsonl and to stdout with the
headline line re-echoed after every record. The driver only ever runs ``python
bench.py``, so this is how a healthy relay window banks the whole session
with no operator in the loop.

Throughput definition replicates the reference's
(examples/training/llama/training_utils.py:329-351: moving-window seqs/s,
converted here to tokens/sec/chip, the BASELINE.json primary metric).
``vs_baseline`` is measured/target where the target is the BASELINE.md MFU
north star (≥45% MFU) converted to tokens/sec for this chip+model, since the
reference repo publishes no absolute numbers (BASELINE.md).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from neuronx_distributed_llama3_2_tpu.models import LLAMA_CONFIGS, LlamaForCausalLM
    from neuronx_distributed_llama3_2_tpu.trainer import (
        OptimizerConfig,
        TrainingConfig,
        initialize_parallel_model,
        make_train_step,
    )
    from neuronx_distributed_llama3_2_tpu.flops import (
        PEAK_FLOPS_PER_CHIP,
        mfu,
        train_flops_per_token,
    )

    # env knobs let scripts/mfu_sweep.py probe alternatives in bounded
    # subprocesses; the committed defaults are the tuned values
    env_int = lambda k, d: int(os.environ.get(k, d))  # noqa: E731
    model_cfg = dataclasses.replace(
        LLAMA_CONFIGS["llama3.2-1b"],
        remat=os.environ.get("BENCH_REMAT", "full"),
        max_seq_len=2048,
        use_flash_attention=True,
        # tuned on v5e: large flash tiles amortize Mosaic per-program
        # overhead (sweep: 256x512 -> 41.7%, 1024x1024 -> 46.0% MFU);
        # chunk 256 beats 512 by ~1 point on the fused CE
        flash_block_q=env_int("BENCH_FLASH_BQ", 1024),
        flash_block_kv=env_int("BENCH_FLASH_BKV", 1024),
        loss_chunk_size=env_int("BENCH_LOSS_CHUNK", 256),
    )
    batch, seq = env_int("BENCH_BATCH", 12), 2048

    # Single-chip 1B: pure-bf16 optimizer (no fp32 master — 12 bytes/param of
    # AdamW state does not fit 16G HBM next to the model; multi-chip ZeRO-1
    # restores fp32 state by sharding it over dp).
    config = TrainingConfig(
        optimizer=OptimizerConfig(
            zero_one_enabled=False,
            warmup_steps=1,
            use_master_weights=False,
            use_fp32_grad_acc=False,
            state_dtype="bfloat16",
        )
    )
    config.initialize()
    model = LlamaForCausalLM(model_cfg)
    state, _ = initialize_parallel_model(model, config)
    step = make_train_step(model, config)

    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, model_cfg.vocab_size, (batch, seq)),
        dtype=jnp.int32,
    )
    data = {"input_ids": ids, "labels": ids}

    # warmup / compile (block via host transfer: on the axon tunnel backend
    # block_until_ready returns before execution completes)
    state, metrics = step(state, data)
    loss0 = float(metrics["loss"])
    if not np.isfinite(loss0):
        raise RuntimeError(f"non-finite loss {loss0} on the bench step")

    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, data)
        float(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters

    tokens_per_sec = batch * seq / dt

    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(state.params))
    # v5e bf16 peak (flops.py — shared with the serving CostProfiles)
    peak = PEAK_FLOPS_PER_CHIP
    measured_mfu = mfu(
        tokens_per_sec,
        n_params,
        model_cfg.num_layers,
        model_cfg.hidden_size,
        seq,
        peak,
    )
    # target tokens/sec at the BASELINE.md 45%-MFU north star
    target_tps = 0.45 * peak / train_flops_per_token(
        n_params, model_cfg.num_layers, model_cfg.hidden_size, seq
    )

    print(
        json.dumps(
            {
                "metric": "llama3.2-1b_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(tokens_per_sec / target_tps, 4),
                "detail": {
                    "mfu": round(measured_mfu, 4),
                    "step_ms": round(dt * 1000, 2),
                    "batch": batch,
                    "seq": seq,
                    "n_params": n_params,
                    "flops_per_token": train_flops_per_token(
                        n_params, model_cfg.num_layers,
                        model_cfg.hidden_size, seq,
                    ),
                    "chip": str(jax.devices()[0]),
                },
            }
        )
    )


METRIC_NAME = "llama3.2-1b_train_tokens_per_sec_per_chip"

_TRANSIENT_MARKERS = ("UNAVAILABLE", "Unable to initialize", "DEADLINE_EXCEEDED")


def _load_chip_session():
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts", "chip_session.py"
    )
    spec = importlib.util.spec_from_file_location("chip_session", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _post_session(headline: "str | None", start: float) -> None:
    """Bank the whole staged chip session after a healthy headline run.

    The driver only ever runs ``python bench.py`` (VERDICT r4 #1), so the
    chip-session stages must ride this invocation: once the headline metric
    is out, the remaining session budget (``BENCH_SESSION_DEADLINE_S``,
    measured from process start) executes the ``scripts/chip_session.py``
    stages in value-per-minute order — MFU margin sweep, chip-side TTFT,
    kernel gate, serving churn, 32K long-context, the head/ring A/B
    default gates, ring-step timing. Each stage's record is appended to
    ``CHIP_SESSION.jsonl`` AND printed to stdout as it completes, with the
    headline line re-echoed after every record so the stream's last
    complete JSON line is always the driver metric, wherever a kill lands.
    ``BENCH_SESSION=0`` disables (set automatically for session *stages*
    so the bench stage of a manual chip_session run can't recurse).
    """
    if os.environ.get("BENCH_SESSION", "1") == "0":
        return
    try:
        # inside the guard: even a malformed env value must never turn a
        # healthy headline run into a nonzero exit
        # (default total budget 7200 s: long enough for the MFU sweep +
        # TTFT + A/B gates on realistic stage durations, while the
        # bank-as-you-go stream + CHIP_SESSION.jsonl keep every completed
        # record — and the echoed headline as the last JSON line — even if
        # a driver with a shorter timeout kills the tail of the session)
        total = float(os.environ.get("BENCH_SESSION_DEADLINE_S", "7200"))
        remaining = total - (time.monotonic() - start)
        if remaining < 180:
            return
        cs = _load_chip_session()
        # headline success already proved the backend is up — skip probe/bench
        stages = [s for s in cs.STAGES if s[0] not in ("probe", "bench")]
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "CHIP_SESSION.jsonl"
        )
        cs.run_session(
            stages,
            deadline_s=remaining,
            out_path=out_path,
            stream=sys.stdout,
            echo_line=headline,
        )
    except Exception as e:  # a broken session must never cost the headline:
        # the driver keys on exit code, and the headline already printed
        print(f"# chip session failed: {e}", file=sys.stderr, flush=True)
        if headline:
            print(headline, flush=True)


def _probe_backend(timeout_s: float = 120.0) -> str:
    """Independent relay probe: bare ``jax.devices()`` in a bounded subprocess.

    Classifies the backend state so a driver artifact alone distinguishes a
    relay outage from a bench regression (the round-3 outage needed prose in
    BENCHMARKS.md to make that call). Returns one of ``"ok"``,
    ``"backend_init_timeout"``, or ``"backend_init_error: <last line>"``.
    """
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices())"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return "backend_init_timeout"
    if proc.returncode == 0:
        return "ok"
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return f"backend_init_error: {tail[-1][:200] if tail else 'unknown'}"


def _emit_failure(reason: str, probe: str | None = None) -> None:
    """One parseable JSON line so an outage yields a failure *record*, not a
    driver-side rc=124 with nothing to parse. ``probe`` carries the
    independent backend-probe classification (None = probe not run)."""
    record = {
        "metric": METRIC_NAME,
        "value": None,
        "unit": "tokens/s",
        "vs_baseline": None,
        "error": reason,
    }
    if probe is not None:
        record["probe"] = probe
    print(json.dumps(record), flush=True)


def _stamp_preflight(out: str, verdict: str) -> str:
    """Stamp the up-front backend-probe verdict into the headline record.

    Finds the last stdout line that parses as the headline JSON object
    (a dict carrying ``"metric"``) and adds ``"preflight": verdict`` —
    provenance that distinguishes "measured against a backend the probe
    saw healthy" from "number out of a relay the probe never vouched for"
    straight from the driver artifact. Unparseable output passes through
    untouched (the headline contract is bench.py's own, but a stamp must
    never corrupt what it cannot parse)."""
    lines = out.splitlines()
    for i in range(len(lines) - 1, -1, -1):
        ln = lines[i].strip()
        if not ln.startswith("{"):
            continue
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if not isinstance(rec, dict) or "metric" not in rec:
            continue
        rec["preflight"] = verdict
        lines[i] = json.dumps(rec)
        return "\n".join(lines) + ("\n" if out.endswith("\n") else "")
    return out


def _launch_once(timeout_s: float):
    """Run ``bench.py --once`` in a subprocess bounded by ``timeout_s``.

    The round-2 outage showed the failure mode is not only a fast
    UNAVAILABLE error: backend *init itself* hung ~25 minutes inside the
    relay, which no in-process retry loop can interrupt. A killed subprocess
    can. Returns ``(status, stdout, stderr)`` with status in
    {"ok", "timeout", "error"}.
    """
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--once"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as e:

        def _s(b):
            return b.decode(errors="replace") if isinstance(b, bytes) else (b or "")

        return "timeout", _s(e.stdout), _s(e.stderr)
    return ("ok" if proc.returncode == 0 else "error"), proc.stdout, proc.stderr


def main_with_retries(
    attempts: int | None = None,
    backoff_s: float | None = None,
    deadline_s: float | None = None,
    attempt_timeout_s: float | None = None,
    launch=_launch_once,
    probe=None,
    post_session=lambda headline, start: None,
) -> None:
    """Retry transient relay outages, bounded in wall-clock.

    Every attempt runs in a subprocess with a hard timeout, and the whole
    loop respects ``deadline_s`` — so the worst case is a fast, parseable
    JSON failure line, never an unbounded hang that eats the driver's
    timeout (round-2 failure mode: BENCH_r02.json rc=124, parsed=null).
    Tunables via env: BENCH_RETRY_ATTEMPTS, BENCH_RETRY_BACKOFF_S,
    BENCH_DEADLINE_S, BENCH_ATTEMPT_TIMEOUT_S.
    """
    if attempts is None:
        attempts = int(os.environ.get("BENCH_RETRY_ATTEMPTS", "3"))
    if backoff_s is None:
        backoff_s = float(os.environ.get("BENCH_RETRY_BACKOFF_S", "15"))
    if deadline_s is None:
        deadline_s = float(os.environ.get("BENCH_DEADLINE_S", "1200"))
    if attempt_timeout_s is None:
        attempt_timeout_s = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT_S", "480"))

    # the probe's wall-clock is reserved out of deadline_s so the WHOLE
    # invocation (probe included) stays under the deadline — the driver
    # must never see rc=124 because our own probe overran
    probe_budget = min(120.0, 0.25 * deadline_s)
    if probe is None:
        probe = lambda: _probe_backend(probe_budget)  # noqa: E731
    loop_deadline = deadline_s - probe_budget

    # relay preflight: one bare jax.devices() probe BEFORE any attempt.
    # The verdict rides every record — "preflight" on the healthy headline,
    # "probe" on failure lines — so a driver artifact alone says whether
    # the number was measured against a backend the probe saw healthy
    # (round-3 needed prose in BENCHMARKS.md to make that call). The loop
    # clock starts after the probe, keeping probe + loop under deadline_s.
    preflight = probe()
    start = time.monotonic()
    last_reason = "no attempts made (deadline exhausted)"
    for i in range(attempts):
        remaining = loop_deadline - (time.monotonic() - start)
        if remaining <= 0:
            break
        status, out, err = launch(min(attempt_timeout_s, remaining))
        if err:
            sys.stderr.write(err)
            sys.stderr.flush()
        if status == "ok":
            out = _stamp_preflight(out, preflight)
            sys.stdout.write(out)
            sys.stdout.flush()
            headline = next(
                (
                    ln
                    for ln in reversed(out.strip().splitlines())
                    if ln.strip().startswith("{")
                ),
                None,
            )
            post_session(headline, start)
            return
        tail = (out + "\n" + err)[-2000:]
        if status == "timeout":
            last_reason = f"attempt {i + 1} timed out after {min(attempt_timeout_s, remaining):.0f}s"
        else:
            last_reason = f"attempt {i + 1} failed: {tail.strip().splitlines()[-1] if tail.strip() else 'unknown'}"
        transient = status == "timeout" or any(m in tail for m in _TRANSIENT_MARKERS)
        if not transient:
            sys.stdout.write(out)
            if out and not out.endswith("\n"):
                sys.stdout.write("\n")  # keep the record on its own line
            # the contract is "every failure mode yields a machine-readable
            # record" — including this one (ADVICE r3)
            _emit_failure(f"non-transient: {last_reason}", probe=preflight)
            raise SystemExit(3)
        remaining = loop_deadline - (time.monotonic() - start)
        if i < attempts - 1 and remaining > backoff_s:
            print(
                f"# backend unavailable ({last_reason}); retrying in {backoff_s:.0f}s",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(backoff_s)

    _emit_failure(f"backend unavailable: {last_reason}", probe=preflight)
    raise SystemExit(2)


if __name__ == "__main__":
    if "--once" in sys.argv[1:]:
        main()
    else:
        # the driver entry point: headline metric first, then bank the
        # staged chip session with the leftover budget (VERDICT r4 #1)
        main_with_retries(post_session=_post_session)
